"""Cross-process fleet plane: the single-process router's contract, one level up.

PR 15 scaled the fleet with chips inside ONE process (mesh-sliced replicas);
this module distributes it across processes/hosts.  Three pieces
(docs/FLEET.md):

- **Wire protocol + peer client.**  Every ``serve`` process exposes a small
  fleet API next to its serving endpoints: ``/fleet/healthz`` (supervision/
  breaker/slice summary), ``/fleet/prefix`` (prefix-registry gossip deltas),
  ``/fleet/kv/put|get`` (prefix KV pages in the PR 12 device-agnostic numpy
  snapshot format, wrapped in the versioned dtype-tagged wire encoding below
  — fp8/int8 pools round-trip bit-exactly), and ``/fleet/generate`` (the
  token-level dialog contract the :class:`FleetRouter` dispatches on).

- **Cross-process prefix registry.**  Each process's :class:`FleetPlane`
  keeps a seq-numbered delta log of its local KV tier-transition events
  (fed by the same listener chain the in-process
  :class:`~.router.FleetPrefixRegistry` reads); followers poll
  ``/fleet/prefix`` and apply the deltas into their OWN FleetPrefixRegistry,
  so affinity routes a returning session to the PROCESS that holds its warm
  pages — and a missing local prefix can be *pulled* from the holder over
  ``/fleet/kv/get`` into the target's host tier ahead of suffix prefill
  (the restore path itself is unchanged).

- **Disaggregated prefill/decode pools.**  A ``--pool`` role knob: prefill
  processes run chunked prefill only (``prefill_only`` requests, background
  class — the scheduler tag that already distinguishes the traffic), write
  finished pages through the host tier, push them to the decode pool over
  the wire, and hand off; decode processes admit via restore and REJECT
  long prefill (``pool_role`` shed), so decode ITL is isolated from
  long-prompt arrivals.  When a whole pool is dead, availability beats role
  purity: the router retries with ``force`` and the bypass is counted.

The :class:`FleetRouter` mirrors :meth:`EngineRouter.submit`'s exact
contract (same kwargs, a ``concurrent.futures.Future`` result) and its
dispatch precedence — health first (peer healthz + per-peer
:class:`~..ai.providers.failover.CircuitBreaker`), prefix affinity second
(the gossip-fed registry), least-loaded last with a rotating tie-break —
with token-less re-route on peer death (non-streaming requests are
token-less by construction until the response lands) and trace_id
propagation end to end.

Thread contract: the router dispatches on a small worker pool (one wire
round-trip per request thread); counters live under one leaf lock; no
future is ever resolved under it (dabtlint DABT102) and every timestamp
flows through the injectable ``clock``/``sleep`` (DABT105).
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import threading
import time
import urllib.parse
from collections import OrderedDict, deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ai.providers.failover import CircuitBreaker
from .engine import EngineUnavailable
from .faults import FaultInjector, global_injector
from .kv_pool import (
    KV_WIRE_COMPAT_VERSIONS,
    KV_WIRE_VERSION,
    TIER_DISK,
    TIER_HBM,
    TIER_HOST,
    HostPrefixEntry,
    WireDecodeError,
    WireIntegrityError,
    WireVersionError,
)
from ..storage.integrity import crc32c, entry_crc32c
from .obs import FlightRecorder, new_trace_id
from .scheduler import DeadlineExceeded, SchedulerRejected

logger = logging.getLogger(__name__)

_TIER_RANK = {TIER_HBM: 0, TIER_HOST: 1, TIER_DISK: 2}

# ---------------------------------------------------------------- wire codec
# Layout: MAGIC | uint32-LE header length | JSON header | k bytes | v bytes.
# The header is dtype-tagged exactly like the PR 12 disk format (raw uint8
# views + a dtype STRING re-resolved on the receiver), so fp8/bf16/int8
# pools round-trip bit-exactly across processes and builds that agree on
# KV_WIRE_VERSION — and fail loudly across builds that don't.  Since wire v2
# the header also carries a CRC-32C of the k+v body, verified on decode; v1
# payloads (no checksum) still decode, per KV_WIRE_COMPAT_VERSIONS.
KV_WIRE_MAGIC = b"DABTKV"

# The versions THIS decoder accepts (module-level so a test can emulate an
# old decoder meeting a new payload by narrowing it).
WIRE_ACCEPT_VERSIONS = KV_WIRE_COMPAT_VERSIONS


def _resolve_dtype(name: str) -> np.dtype:
    """``np.dtype`` from its string name; ml_dtypes names (float8_e4m3fn,
    bfloat16, ...) resolve once ml_dtypes has registered them."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # noqa: F401  (registers the extended dtypes)

        return np.dtype(name)


def encode_kv_entry(entry: HostPrefixEntry) -> bytes:
    """One :class:`HostPrefixEntry` -> wire bytes (see module docstring)."""
    k = np.ascontiguousarray(entry.k)
    v = np.ascontiguousarray(entry.v)
    header = {
        "wire_version": KV_WIRE_VERSION,
        "key": [int(t) for t in entry.key],
        "length": int(entry.length),
        "dtype": str(k.dtype),
        "k_shape": list(k.shape),
        "v_shape": list(v.shape),
        "k_nbytes": int(k.nbytes),
        "v_nbytes": int(v.nbytes),
        "crc32c": entry_crc32c(k, v),
    }
    hb = json.dumps(header, separators=(",", ":")).encode("utf-8")
    return b"".join(
        [
            KV_WIRE_MAGIC,
            len(hb).to_bytes(4, "little"),
            hb,
            k.view(np.uint8).tobytes(),
            v.view(np.uint8).tobytes(),
        ]
    )


def decode_kv_entry(data: bytes) -> HostPrefixEntry:
    """Wire bytes -> :class:`HostPrefixEntry` (numpy arrays in the sender's
    exact dtype).  Raises :class:`WireVersionError` for a payload stamped by
    a build outside ``WIRE_ACCEPT_VERSIONS``, :class:`WireIntegrityError`
    when the payload's CRC-32C does not match its bytes, and
    :class:`WireDecodeError` for anything malformed (truncation at any
    envelope boundary, bad magic, unreadable header, body/metadata mismatch)
    — the receiver must never guess at bytes it cannot prove it understands.
    All three are ``ValueError`` subclasses, so pre-CRC callers still catch
    them."""
    m = len(KV_WIRE_MAGIC)
    if len(data) < m + 4 or data[:m] != KV_WIRE_MAGIC:
        raise WireDecodeError("not a DABT KV wire payload (bad magic)")
    hlen = int.from_bytes(data[m : m + 4], "little")
    if len(data) < m + 4 + hlen:
        raise WireDecodeError("truncated KV wire payload (header)")
    try:
        header = json.loads(data[m + 4 : m + 4 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise WireDecodeError(f"unreadable KV wire header: {e}") from None
    if not isinstance(header, dict):
        raise WireDecodeError("unreadable KV wire header: not a JSON object")
    ver = header.get("wire_version")
    if ver not in WIRE_ACCEPT_VERSIONS:
        raise WireVersionError(
            f"KV wire payload has wire_version {ver!r} (this build accepts "
            f"{tuple(WIRE_ACCEPT_VERSIONS)}); refusing to decode cross-build "
            "pages"
        )
    try:
        dtype = _resolve_dtype(str(header["dtype"]))
        k_nbytes = int(header["k_nbytes"])
        v_nbytes = int(header["v_nbytes"])
        k_shape = [int(d) for d in header["k_shape"]]
        v_shape = [int(d) for d in header["v_shape"]]
        key = tuple(int(t) for t in header["key"])
        length = int(header["length"])
    except (KeyError, TypeError, ValueError) as e:
        raise WireDecodeError(f"malformed KV wire header: {e}") from None
    body = data[m + 4 + hlen :]
    if len(body) != k_nbytes + v_nbytes:
        raise WireDecodeError(
            f"KV wire payload body is {len(body)} bytes; header promised "
            f"{k_nbytes + v_nbytes}"
        )
    # v2+: the body must prove itself against the header checksum BEFORE any
    # bytes are reinterpreted as pages.  v1 carried none — accepted as-is.
    crc = header.get("crc32c")
    if ver >= 2:
        if not isinstance(crc, int):
            raise WireDecodeError("KV wire v2 payload is missing its crc32c")
        actual = crc32c(body)
        if actual != crc:
            raise WireIntegrityError(
                f"KV wire payload failed its CRC-32C (stored {crc:#010x}, "
                f"computed {actual:#010x}) — corrupt in flight; rejecting"
            )
    try:
        k = (
            np.frombuffer(body, np.uint8, count=k_nbytes)
            .view(dtype)
            .reshape(k_shape)
        )
        v = (
            np.frombuffer(body, np.uint8, count=v_nbytes, offset=k_nbytes)
            .view(dtype)
            .reshape(v_shape)
        )
    except ValueError as e:
        raise WireDecodeError(f"KV wire payload shape mismatch: {e}") from None
    if length != len(key) or length <= 0:
        raise WireDecodeError("KV wire payload key/length mismatch")
    return HostPrefixEntry(
        key=key,
        length=length,
        k=k,
        v=v,
        nbytes=k_nbytes + v_nbytes,
        pages=0,  # receiver recomputes against its OWN page size on put
        wire_version=int(ver),
        crc32c=crc if isinstance(crc, int) else None,
    )


# --------------------------------------------------------------- peer client
class PeerUnreachable(RuntimeError):
    """Connection-level failure: the peer process is dead, unreachable, or
    timed out before producing a status line — replica-death-shaped, so the
    router may re-route a token-less request.

    ``phase`` distinguishes WHERE the wire died, because the safe recovery
    differs: ``"connect"`` means the request never left this process (free to
    retry or re-route), ``"read"`` means it was already on the wire when the
    connection died — the peer may well have executed it, so the router
    retries the SAME peer under the request's idempotency key instead of
    re-routing into a double execution."""

    def __init__(self, detail: str, *, phase: str = "connect"):
        super().__init__(detail)
        self.phase = phase


class PeerHTTPError(RuntimeError):
    """The peer answered with a non-2xx status.  ``retry_after_s`` carries
    the peer's own ``Retry-After`` hint (429/503 — the PR 5 policy);
    ``reason`` the shed reason when the body had one."""

    def __init__(
        self,
        status: int,
        detail: str,
        *,
        retry_after_s: Optional[float] = None,
        reason: str = "",
    ):
        super().__init__(f"peer HTTP {status}: {detail}")
        self.status = int(status)
        self.detail = detail
        self.retry_after_s = retry_after_s
        self.reason = reason


def _chain_digest(digest: int, ev: dict) -> int:
    """Fold one gossip event into a rolling CRC32C chain.  Both sides (the
    plane's append path and the router's delta-apply path) fold the SAME
    canonical JSON encoding, so equal logs yield equal digests and a
    diverged ``/fleet/prefix`` log is detectable in one integer compare."""
    blob = json.dumps(ev, sort_keys=True, separators=(",", ":")).encode("utf-8")
    return crc32c(blob, digest)


def _flip_one_byte(data: bytes) -> bytes:
    """The ``net_corrupt`` payload mutation: one bit of the middle byte —
    exactly the failure a checksum exists to catch, deterministic so the
    chaos bench's injected-vs-rejected accounting is exact."""
    if not data:
        return data
    out = bytearray(data)
    out[len(out) // 2] ^= 0x01
    return bytes(out)


class PeerClient:
    """Tiny synchronous HTTP client for the fleet wire (stdlib only — the
    serving container ships no HTTP client library).  One request per call,
    no connection reuse: peers are long-lived but requests must never share
    failure state across threads.

    The single legacy ``timeout_s`` is split: ``connect_timeout_s`` bounds
    the TCP connect (a black-holed SYN fails in seconds, not the full
    request budget) while ``timeout_s`` — overridable per call — bounds the
    read, so a long KV transfer still completes.  Failures carry the phase
    (:class:`PeerUnreachable`); ``retries`` re-attempts CONNECT-phase
    failures only (nothing reached the peer) with exponential backoff
    through the injectable ``sleep``.

    Network chaos: when a :class:`~.faults.FaultInjector` is attached (or
    the env-gated global one exists), the ``net_*`` sites are consulted per
    request under ``fault_key`` — the caller's ``"self->peer"`` edge string
    — so each edge replays its own seeded schedule (see serving/faults.py)."""

    def __init__(
        self,
        base_url: str,
        *,
        timeout_s: float = 30.0,
        connect_timeout_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        injector: Optional[FaultInjector] = None,
        fault_key: str = "",
    ):
        self.base_url = base_url.rstrip("/")
        self.timeout_s = float(timeout_s)
        self.connect_timeout_s = (
            float(connect_timeout_s)
            if connect_timeout_s is not None
            else min(5.0, self.timeout_s)
        )
        self._clock = clock
        self._sleep = sleep
        self._injector = injector
        self.fault_key = fault_key

    def _inj(self) -> Optional[FaultInjector]:
        return self._injector if self._injector is not None else global_injector()

    def _request(
        self,
        method: str,
        path: str,
        *,
        body: Optional[bytes] = None,
        content_type: str = "application/json",
        timeout_s: Optional[float] = None,
        headers: Optional[Dict[str, str]] = None,
        retries: int = 0,
    ) -> Tuple[int, bytes]:
        attempt = 0
        while True:
            try:
                return self._request_once(
                    method,
                    path,
                    body=body,
                    content_type=content_type,
                    timeout_s=timeout_s,
                    headers=headers,
                )
            except PeerUnreachable as e:
                # only connect-phase failures are provably un-executed and
                # safe to blindly re-send; read-phase recovery belongs to the
                # router, which holds the idempotency key
                if attempt >= int(retries) or e.phase != "connect":
                    raise
                attempt += 1
                self._sleep(min(1.0, 0.05 * (2 ** (attempt - 1))))

    def _request_once(
        self,
        method: str,
        path: str,
        *,
        body: Optional[bytes],
        content_type: str,
        timeout_s: Optional[float],
        headers: Optional[Dict[str, str]],
    ) -> Tuple[int, bytes]:
        inj = self._inj()
        edge = self.fault_key
        if inj is not None:
            if inj.should_fire("net_partition", edge):
                raise PeerUnreachable(
                    f"{self.base_url}: injected net_partition (connection refused)",
                    phase="connect",
                )
            if inj.should_fire("net_blackhole", edge):
                raise PeerUnreachable(
                    f"{self.base_url}: injected net_blackhole (connect timed "
                    f"out after {self.connect_timeout_s}s)",
                    phase="connect",
                )
            d = inj.sleep_s("net_delay", edge)
            if d > 0:
                self._sleep(d)
            if (
                body is not None
                and content_type == "application/octet-stream"
                and inj.should_fire("net_corrupt", edge)
            ):
                body = _flip_one_byte(body)
        sp = urllib.parse.urlsplit(self.base_url + path)
        conn_cls = (
            http.client.HTTPSConnection
            if sp.scheme == "https"
            else http.client.HTTPConnection
        )
        conn = conn_cls(sp.netloc, timeout=self.connect_timeout_s)
        try:
            try:
                conn.connect()
            except (OSError, TimeoutError) as e:
                raise PeerUnreachable(
                    f"{self.base_url}: {e}", phase="connect"
                ) from None
            sock = getattr(conn, "sock", None)
            if sock is not None:
                read_timeout = (
                    float(timeout_s) if timeout_s is not None else self.timeout_s
                )
                sock.settimeout(max(0.001, read_timeout))
            target = (sp.path or "/") + (f"?{sp.query}" if sp.query else "")
            try:
                conn.request(
                    method,
                    target,
                    body=body,
                    headers={"Content-Type": content_type, **(headers or {})},
                )
                if inj is not None and inj.should_fire("net_drop", edge):
                    # the request is already on the wire: the peer may be
                    # executing it right now — read-phase failure semantics
                    raise PeerUnreachable(
                        f"{self.base_url}: injected net_drop (connection lost "
                        "awaiting response)",
                        phase="read",
                    )
                resp = conn.getresponse()
                data = resp.read()
                status = int(resp.status)
                resp_ct = resp.headers.get("Content-Type", "") or ""
                retry_hdr = resp.headers.get("Retry-After")
            except PeerUnreachable:
                raise
            except (http.client.HTTPException, OSError, TimeoutError) as e:
                # post-connect death: the request MAY have been received and
                # executed — the phase tells the router to dedup, not re-run
                raise PeerUnreachable(
                    f"{self.base_url}: {e!r}", phase="read"
                ) from None
        finally:
            conn.close()
        if (
            inj is not None
            and status < 400
            and resp_ct.startswith("application/octet-stream")
            and inj.should_fire("net_corrupt", edge)
        ):
            data = _flip_one_byte(data)
        if status >= 400:
            detail, reason, retry = f"HTTP {status}", "", None
            try:
                payload = json.loads(data.decode("utf-8"))
                detail = str(payload.get("detail", detail))
                reason = str(payload.get("reason", ""))
                if "retry_after_s" in payload:
                    retry = float(payload["retry_after_s"])
            except Exception:
                pass
            if retry is None and retry_hdr is not None:
                try:
                    retry = float(retry_hdr)
                except ValueError:
                    retry = None
            raise PeerHTTPError(status, detail, retry_after_s=retry, reason=reason)
        return status, data

    def get_json(
        self, path: str, *, timeout_s: Optional[float] = None, retries: int = 0
    ) -> dict:
        _, data = self._request("GET", path, timeout_s=timeout_s, retries=retries)
        return json.loads(data.decode("utf-8"))

    def post_json(
        self, path: str, body: dict, *, timeout_s: Optional[float] = None
    ) -> dict:
        _, data = self._request(
            "POST",
            path,
            body=json.dumps(body).encode("utf-8"),
            timeout_s=timeout_s,
        )
        return json.loads(data.decode("utf-8"))

    def post_for_bytes(
        self, path: str, body: dict, *, timeout_s: Optional[float] = None
    ) -> Optional[bytes]:
        """POST JSON, expect raw bytes back; None on 404 (an honest miss,
        not an error — the /fleet/kv/get contract)."""
        try:
            _, data = self._request(
                "POST",
                path,
                body=json.dumps(body).encode("utf-8"),
                timeout_s=timeout_s,
            )
        except PeerHTTPError as e:
            if e.status == 404:
                return None
            raise
        return data

    def post_bytes(
        self, path: str, data: bytes, *, timeout_s: Optional[float] = None
    ) -> dict:
        _, out = self._request(
            "POST",
            path,
            body=data,
            content_type="application/octet-stream",
            timeout_s=timeout_s,
        )
        return json.loads(out.decode("utf-8"))


# ---------------------------------------------------------------- fleet peer
class FleetPeer:
    """One remote ``serve`` process as the router sees it: address, circuit
    breaker, pool role, and the last health/load/gossip snapshot."""

    def __init__(
        self,
        name: str,
        base_url: str,
        *,
        pool: str = "unified",
        breaker: Optional[CircuitBreaker] = None,
        client: Optional[PeerClient] = None,
        timeout_s: float = 30.0,
    ):
        self.name = name
        self.base_url = base_url.rstrip("/")
        self.client = client or PeerClient(base_url, timeout_s=timeout_s)
        self.breaker = breaker or CircuitBreaker()
        self.pool = pool
        self.draining = False
        self.healthy = True  # optimistic until a refresh says otherwise
        self.queued = 0
        self.active = 0
        self.prefix_seq = 0  # gossip cursor into the peer's delta log
        self.prefix_digest = 0  # running CRC chain over the peer's gossip log
        self.dispatched = 0
        self.last_refresh_ok = False
        # partition-tolerance state (FleetRouter.refresh owns all of it):
        # when the peer was last CONFIRMED reachable, when the current
        # unreachable streak began, whether its gossip-learned holdings were
        # TTL-dropped, why the last refresh failed, and — on heal — when the
        # forced anti-entropy resync started (convergence gauge)
        self.last_confirmed: Optional[float] = None
        self.unreachable_since: Optional[float] = None
        self.ttl_dropped = False
        self.last_failure_reason = ""
        self.resync_started_at: Optional[float] = None

    def load(self) -> int:
        return self.queued + self.active


class _FleetRequest:
    """Mutable per-request dispatch state (one worker thread owns it)."""

    __slots__ = (
        "prompt_ids",
        "body",
        "prefix_len",
        "deadline_at",
        "trace_id",
        "hops",
        "affinity_hit",
        "forced",
        "timeout_retries_used",
    )

    def __init__(self, prompt_ids, body, prefix_len, deadline_at, trace_id):
        self.prompt_ids = prompt_ids
        self.body = body
        self.prefix_len = prefix_len
        self.deadline_at = deadline_at
        self.trace_id = trace_id
        self.hops = 0
        self.affinity_hit = False
        self.forced = False
        self.timeout_retries_used = 0


class FleetResult:
    """What a fleet dispatch resolves to — the token-level subset of
    :class:`~.engine.GenerationResult` plus fleet routing metadata.  Token
    ids are the bit-identity surface (text is the peer's detokenization)."""

    def __init__(
        self,
        *,
        token_ids: List[int],
        text: str,
        prompt_tokens: int,
        completion_tokens: int,
        length_limited: bool,
        peer: str,
        reroutes: int,
        trace_id: str,
        handoff: Optional[dict] = None,
    ):
        self.token_ids = token_ids
        self.text = text
        self.prompt_tokens = prompt_tokens
        self.completion_tokens = completion_tokens
        self.length_limited = length_limited
        self.peer = peer
        self.reroutes = reroutes
        self.trace_id = trace_id
        self.handoff = handoff

    def usage_dict(self, model: str) -> dict:
        return {
            "model": model,
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.prompt_tokens + self.completion_tokens,
            "peer": self.peer,
        }


# -------------------------------------------------------------- fleet router
class FleetRouter:
    """Dispatch dialog requests across ``serve`` PROCESSES with the
    in-process router's exact submit contract and precedence (health >
    affinity > least-loaded), per-peer circuit breakers, token-less re-route
    on peer death, and — when the fleet is disaggregated — the two-stage
    prefill-pool -> decode-pool handoff.

    ``peers`` is a sequence of ``(name, base_url)`` pairs or
    :class:`FleetPeer` objects.  ``refresh()`` polls every peer's
    ``/fleet/healthz`` and ``/fleet/prefix`` (gossip) — called lazily from
    dispatch when the last poll is older than ``refresh_interval_s``, or
    continuously via :meth:`start`.
    """

    def __init__(
        self,
        peers: Sequence[Any],
        *,
        model: str,
        name: str = "router",
        breaker_threshold: int = 3,
        breaker_reset_s: float = 10.0,
        max_reroutes: int = 2,
        request_timeout_s: float = 300.0,
        connect_timeout_s: float = 5.0,
        health_timeout_s: float = 5.0,
        refresh_interval_s: float = 2.0,
        registry_ttl_s: float = 30.0,
        timeout_retries: int = 1,
        handoff_suffix_tokens: int = 64,
        pull_min_tokens: int = 1,
        max_workers: int = 8,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        injector: Optional[FaultInjector] = None,
    ):
        from .router import FleetPrefixRegistry

        self.model = model
        self.name = str(name)
        self.max_reroutes = max(0, int(max_reroutes))
        self.request_timeout_s = float(request_timeout_s)
        self.connect_timeout_s = float(connect_timeout_s)
        self.health_timeout_s = float(health_timeout_s)
        self.refresh_interval_s = float(refresh_interval_s)
        # how long a peer may stay unreachable before the affinity claims we
        # learned from its gossip age out of the registry (partition
        # tolerance: a dead link must stop attracting traffic)
        self.registry_ttl_s = float(registry_ttl_s)
        # read-phase failures re-try the SAME peer this many times before the
        # peer counts as dead — paired with the idempotency key, the retry
        # returns the original result instead of double-executing
        self.timeout_retries = max(0, int(timeout_retries))
        self.handoff_suffix_tokens = int(handoff_suffix_tokens)
        self.pull_min_tokens = max(1, int(pull_min_tokens))
        self._clock = clock
        self._sleep = sleep
        self.peers: List[FleetPeer] = []
        for p in peers:
            if isinstance(p, FleetPeer):
                self.peers.append(p)
            else:
                peer_name, url = p
                self.peers.append(
                    FleetPeer(
                        peer_name,
                        url,
                        breaker=CircuitBreaker(
                            breaker_threshold, breaker_reset_s, clock=clock
                        ),
                        client=PeerClient(
                            url,
                            timeout_s=request_timeout_s,
                            connect_timeout_s=connect_timeout_s,
                            clock=clock,
                            sleep=sleep,
                            injector=injector,
                            fault_key=f"{self.name}->{peer_name}",
                        ),
                    )
                )
        if not self.peers:
            raise ValueError("FleetRouter needs at least one peer")
        self.prefix_registry = FleetPrefixRegistry()
        self.flight = FlightRecorder(name=f"fleet-{model}", clock=clock)
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(max_workers)),
            thread_name_prefix=f"fleet-{model}",
        )
        self._lock = threading.Lock()
        self._rr = 0
        self._last_refresh = float("-inf")
        self._peer_reps: Dict[str, set] = {}  # peer -> namespaced sub-replicas
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # counters (stats() / the dabt_fleet_* metric surface)
        self.reroutes = 0
        self.rerouted_failed = 0
        self.no_peer_available = 0
        self.sheds = 0
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.prefix_pulls = 0
        self.pull_misses = 0
        self.pull_failures = 0
        self.pages_shipped = 0
        self.handoffs = 0
        self.handoff_fallbacks = 0
        self.pool_role_bypasses = 0
        self.refresh_failures = 0
        self.refresh_failure_reasons: Dict[str, int] = {}
        self.ttl_drops = 0
        self.gossip_digest_mismatches = 0
        self.reconciles = 0
        self.reconcile_last_s = 0.0  # heal -> snapshot-applied convergence
        self.timeout_retries_total = 0
        self.pull_integrity_rejects = 0
        self.pull_refetches = 0

    # ------------------------------------------------------------- lifecycle
    def start(self) -> "FleetRouter":
        """Run :meth:`refresh` on a daemon thread every
        ``refresh_interval_s`` (tests and the bench drive refresh()
        directly instead)."""
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._refresh_loop, name=f"fleet-{self.model}-refresh",
            daemon=True,
        )
        self._thread.start()
        return self

    def _refresh_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.refresh()
            except Exception:
                logger.exception("fleet refresh failed")
            self._stop.wait(self.refresh_interval_s)

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=max(5.0, 2 * self.refresh_interval_s))
        self._thread = None
        self._pool.shutdown(wait=False, cancel_futures=True)

    # --------------------------------------------------------------- refresh
    def refresh(self) -> None:
        """One poll of every peer: health/load off ``/fleet/healthz``,
        prefix gossip off ``/fleet/prefix?since=<cursor>``.  An unreachable
        peer is marked unhealthy AND fed to its breaker, so dispatch skips
        it without paying a connect timeout per request; WHY it failed
        (timeout vs conn-refused vs 5xx) is classified into
        ``refresh_failure_reasons`` and the flight recorder.  A peer
        unreachable past ``registry_ttl_s`` has its gossip-learned holdings
        dropped (its affinity claims stop attracting traffic); on heal its
        log is reconciled via a forced reset-snapshot exchange and the
        convergence time lands in ``reconcile_last_s``."""
        for peer in list(self.peers):
            try:
                hz = peer.client.get_json(
                    "/fleet/healthz?peers=0", timeout_s=self.health_timeout_s
                )
            except (PeerUnreachable, PeerHTTPError, ValueError) as e:
                self._note_refresh_failure(peer, e)
                continue
            self._note_refresh_success(peer)
            status = hz.get("status", "ok")
            peer.healthy = status in ("ok", "degraded")
            peer.draining = status == "draining"
            peer.last_refresh_ok = True
            fleet = hz.get("fleet", {})
            if fleet.get("pool"):
                peer.pool = fleet["pool"]
            load = hz.get("load", {})
            peer.queued = int(load.get("queued", 0))
            peer.active = int(load.get("active", 0))
            try:
                self._poll_prefix(peer)
            except (PeerUnreachable, PeerHTTPError, ValueError, KeyError):
                logger.warning(
                    "fleet prefix poll failed for %s", peer.name, exc_info=True
                )
        with self._lock:
            self._last_refresh = self._clock()

    @staticmethod
    def _failure_reason(exc: BaseException) -> str:
        """Classify a refresh failure for the reason-labelled gauge: the
        operator triaging a partition needs 'timeout' vs 'conn_refused' vs
        'http_5xx' at a glance, not a generic failure count."""
        if isinstance(exc, PeerHTTPError):
            return f"http_{exc.status // 100}xx"
        if isinstance(exc, PeerUnreachable):
            if getattr(exc, "phase", "connect") == "read":
                return "timeout"
            text = str(exc).lower()
            if "refused" in text:
                return "conn_refused"
            if "timed out" in text or "timeout" in text:
                return "timeout"
            return "unreachable"
        return "bad_payload"

    def _note_refresh_failure(self, peer: FleetPeer, exc: BaseException) -> None:
        reason = self._failure_reason(exc)
        was_healthy = peer.healthy
        if peer.healthy or not peer.last_refresh_ok:
            peer.breaker.record_failure()
        peer.healthy = False
        peer.last_refresh_ok = False
        peer.last_failure_reason = reason
        now = self._clock()
        if peer.unreachable_since is None:
            peer.unreachable_since = now
        with self._lock:
            self.refresh_failures += 1
            self.refresh_failure_reasons[reason] = (
                self.refresh_failure_reasons.get(reason, 0) + 1
            )
        if was_healthy:
            self.flight.record(
                "peer_unhealthy", peer=peer.name, reason=reason,
                detail=str(exc)[:200],
            )
        if (
            not peer.ttl_dropped
            and now - peer.unreachable_since >= self.registry_ttl_s
        ):
            dropped = self._drop_peer_holdings(peer)
            peer.ttl_dropped = True
            with self._lock:
                self.ttl_drops += 1
            self.flight.record(
                "registry_ttl_drop",
                peer=peer.name,
                reason=reason,
                entries=dropped,
                unreachable_s=round(now - peer.unreachable_since, 3),
            )

    def _note_refresh_success(self, peer: FleetPeer) -> None:
        now = self._clock()
        if peer.unreachable_since is not None and peer.ttl_dropped:
            # heal after a TTL drop: our view of the peer's log is stale by
            # construction — force the anti-entropy reset-snapshot exchange
            # and time the convergence (resync_started_at -> snapshot applied)
            peer.resync_started_at = now
            peer.prefix_seq = -1  # always predates the log window -> reset
        peer.unreachable_since = None
        peer.ttl_dropped = False
        peer.last_failure_reason = ""
        peer.last_confirmed = now

    def _drop_peer_holdings(self, peer: FleetPeer) -> int:
        """Drop every registry holding learned from this peer's gossip
        (namespaced sub-replicas aggregate to the process)."""
        with self._lock:
            names = set(self._peer_reps.get(peer.name, ()))
        dropped = 0
        for nm in names:
            dropped += int(self.prefix_registry.drop_replica(nm) or 0)
        return dropped

    def _note_rep(self, peer_name: str, namespaced: str) -> None:
        with self._lock:
            self._peer_reps.setdefault(peer_name, set()).add(namespaced)

    def _poll_prefix(self, peer: FleetPeer, *, depth: int = 0) -> None:
        pj = peer.client.get_json(
            f"/fleet/prefix?since={peer.prefix_seq}",
            timeout_s=self.health_timeout_s,
        )
        server_digest = pj.get("digest")
        if pj.get("reset"):
            # the peer's delta log was trimmed (or restarted) past our
            # cursor: drop its holdings and re-apply the snapshot
            self._drop_peer_holdings(peer)
            for h in pj.get("holdings", []):
                if h.get("model") != self.model:
                    continue
                nm = f"{peer.name}/{h['replica']}"
                self._note_rep(peer.name, nm)
                self.prefix_registry.apply_holding(
                    nm, tuple(h["key"]), int(h["length"]), h.get("tier", TIER_HOST)
                )
            # a snapshot is authoritative: adopt the server's digest as the
            # new chain base for subsequent deltas
            if server_digest is not None:
                peer.prefix_digest = int(server_digest)
            if peer.resync_started_at is not None:
                elapsed = self._clock() - peer.resync_started_at
                peer.resync_started_at = None
                with self._lock:
                    self.reconciles += 1
                    self.reconcile_last_s = float(elapsed)
                self.flight.record(
                    "gossip_reconciled",
                    peer=peer.name,
                    reconcile_s=round(elapsed, 4),
                )
        else:
            # chain the digest over EVERY event in the delta (the server
            # digest covers its whole log, not one model's slice)
            d = peer.prefix_digest
            for ev in pj.get("events", []):
                d = _chain_digest(d, ev)
                if ev.get("model") != self.model:
                    continue
                nm = f"{peer.name}/{ev['replica']}"
                self._note_rep(peer.name, nm)
                self.prefix_registry.on_event(
                    nm, ev["event"], tuple(ev["key"]), int(ev["length"])
                )
            peer.prefix_digest = d
            if (
                server_digest is not None
                and int(server_digest) != d
                and depth == 0
            ):
                # diverged logs (missed delta, disagreeing builds): never
                # skew affinity silently — force the reset-snapshot path now
                with self._lock:
                    self.gossip_digest_mismatches += 1
                self.flight.record(
                    "gossip_digest_mismatch",
                    peer=peer.name,
                    ours=d,
                    theirs=int(server_digest),
                )
                if peer.resync_started_at is None:
                    peer.resync_started_at = self._clock()
                peer.prefix_seq = -1
                return self._poll_prefix(peer, depth=depth + 1)
        peer.prefix_seq = int(pj.get("seq", peer.prefix_seq))

    def _maybe_refresh(self) -> None:
        with self._lock:
            stale = self._clock() - self._last_refresh >= self.refresh_interval_s
        if stale:
            self.refresh()

    # -------------------------------------------------------------- dispatch
    def submit(
        self,
        prompt_ids: Sequence[int],
        *,
        max_tokens: int = 1024,
        temperature: float = 0.8,
        top_p: float = 0.95,
        json_format: bool = False,
        prefix_len: int = 0,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        stream: Any = None,
        trace_id: Optional[str] = None,
        attempt: int = 0,
    ) -> Future:
        """The :meth:`EngineRouter.submit` contract over the wire.  Returns
        a ``Future[FleetResult]``; raises synchronously only for contract
        violations (streams do not cross the wire — attach them at a peer).

        ``attempt`` is the CALLER's retry ordinal: it feeds the idempotency
        key (``trace_id:attempt``), so a caller-level retry that WANTS a
        fresh execution bumps it, while the router's own internal
        timeout-retries reuse the same key and dedup server-side."""
        if stream is not None:
            raise ValueError(
                "FleetRouter does not stream across processes; send streaming "
                "requests to a serving peer's /dialog/ directly"
            )
        trace_id = trace_id or new_trace_id()
        prompt_ids = [int(t) for t in prompt_ids]
        prefix_len = max(0, min(int(prefix_len), max(0, len(prompt_ids) - 1)))
        body = {
            "model": self.model,
            "prompt_ids": prompt_ids,
            "max_tokens": int(max_tokens),
            "temperature": float(temperature),
            "top_p": float(top_p),
            "json_format": bool(json_format),
            "prefix_len": prefix_len,
            "priority": priority,
            "tenant": tenant,
            "trace_id": trace_id,
            "idem_key": f"{trace_id}:{int(attempt)}",
        }
        deadline_at = (
            self._clock() + float(deadline_s) if deadline_s is not None else None
        )
        if deadline_s is not None:
            body["deadline_s"] = float(deadline_s)
        st = _FleetRequest(prompt_ids, body, prefix_len, deadline_at, trace_id)
        fut: Future = Future()
        self._pool.submit(self._run, st, fut)
        return fut

    def _run(self, st: _FleetRequest, fut: Future) -> None:
        try:
            self._maybe_refresh()
            if self._disaggregated(st):
                result = self._run_disagg(st)
            else:
                peer, resp = self._dispatch_loop(st, st.body, roles=None)
                result = self._result_from(resp, peer, st)
        except BaseException as e:  # noqa: BLE001 — the future carries it
            if not fut.set_running_or_notify_cancel():
                return
            fut.set_exception(e)
        else:
            if not fut.set_running_or_notify_cancel():
                return
            fut.set_result(result)

    def _disaggregated(self, st: _FleetRequest) -> bool:
        """Handoff when both pools exist AND the un-cached suffix is long
        enough that a decode peer would (rightly) refuse to prefill it."""
        have_prefill = any(
            p.pool == "prefill" and not p.draining for p in self.peers
        )
        have_decode = any(
            p.pool == "decode" and not p.draining for p in self.peers
        )
        if not (have_prefill and have_decode):
            return False
        return len(st.prompt_ids) - st.prefix_len >= self.handoff_suffix_tokens

    def _remaining(self, st: _FleetRequest) -> Optional[float]:
        if st.deadline_at is None:
            return None
        return st.deadline_at - self._clock()

    def _peer_holders(self, prompt_ids, prefix_len) -> Dict[str, int]:
        """peer name -> best tier rank over the gossip-fed registry (the
        namespaced sub-replica holdings aggregate up to their process)."""
        out: Dict[str, int] = {}
        for rep, tier in self.prefix_registry.holders(
            prompt_ids, prefix_len
        ).items():
            peer = rep.split("/", 1)[0]
            r = _TIER_RANK.get(tier, 9)
            if r < out.get(peer, 9):
                out[peer] = r
        return out

    def _candidate_order(
        self,
        st: _FleetRequest,
        excluded: set,
        roles: Optional[Tuple[str, ...]],
        prefer: Optional[str] = None,
    ) -> Tuple[List[FleetPeer], Dict[str, int]]:
        with self._lock:
            self._rr += 1
            rr = self._rr
            peers = list(self.peers)
        n = max(1, len(peers))
        pos = {p.name: i for i, p in enumerate(peers)}
        cands = [
            p
            for p in peers
            if p.name not in excluded
            and not p.draining
            and (roles is None or p.pool in roles)
        ]
        holders = self._peer_holders(st.prompt_ids, st.prefix_len)
        cands.sort(
            key=lambda p: (
                p.name != prefer,
                not p.healthy,
                p.name not in holders,
                holders.get(p.name, 9),
                p.load(),
                (pos[p.name] - rr) % n,
            )
        )
        return cands, holders

    def _dispatch_loop(
        self,
        st: _FleetRequest,
        body: dict,
        roles: Optional[Tuple[str, ...]],
        prefer: Optional[str] = None,
    ) -> Tuple[FleetPeer, dict]:
        """The re-route loop: walk candidates in precedence order, POST
        ``/fleet/generate``, re-route token-less failures up to
        ``max_reroutes`` extra hops.  Sheds (429) exclude the peer and move
        on; when EVERY reject was ``pool_role`` the loop retries once with
        ``force`` — availability beats role purity when a pool is gone."""
        excluded: set = set()
        sheds: List[float] = []
        shed_reasons: List[str] = []
        breaker_waits: List[float] = []
        while True:
            rem = self._remaining(st)
            if rem is not None and rem <= 0:
                raise DeadlineExceeded(
                    f"fleet deadline expired after {st.hops} hops"
                )
            cands, holders = self._candidate_order(st, excluded, roles, prefer)
            peer = None
            for cand in cands:
                if not cand.breaker.allow():
                    breaker_waits.append(cand.breaker.retry_in_s())
                    continue
                peer = cand
                break
            if peer is None:
                if (
                    sheds
                    and shed_reasons
                    and all(r == "pool_role" for r in shed_reasons)
                    and not body.get("force")
                ):
                    # the only objection was pool role — bypass it rather
                    # than fail a servable request (counted, flight-recorded)
                    body = {**body, "force": True}
                    st.forced = True
                    with self._lock:
                        self.pool_role_bypasses += 1
                    self.flight.record(
                        "pool_role_bypass", trace_id=st.trace_id, roles=roles
                    )
                    excluded.clear()
                    sheds.clear()
                    shed_reasons.clear()
                    continue
                with self._lock:
                    self.no_peer_available += 1
                if sheds:
                    with self._lock:
                        self.sheds += 1
                    raise SchedulerRejected("fleet_shed", min(sheds))
                retry = min(breaker_waits) if breaker_waits else 1.0
                raise EngineUnavailable(
                    "no fleet peer available", retry_after_s=max(0.1, retry)
                )
            if peer.name in holders:
                st.affinity_hit = True
                with self._lock:
                    self.affinity_hits += 1
            else:
                with self._lock:
                    self.affinity_misses += 1
                if (
                    holders
                    and st.prefix_len >= self.pull_min_tokens
                    and not body.get("prefill_only")
                ):
                    self._maybe_pull(peer, holders, st)
            timeout = self.request_timeout_s if rem is None else min(
                self.request_timeout_s, rem + 5.0
            )
            if rem is not None:
                body = {**body, "deadline_s": max(0.001, rem)}
            try:
                resp = peer.client.post_json(
                    "/fleet/generate", body, timeout_s=timeout
                )
            except PeerHTTPError as e:
                if e.status == 429:
                    # a shed is back-pressure, not death: never a breaker
                    # failure (half-open probes release instead)
                    peer.breaker.release_probe()
                    excluded.add(peer.name)
                    sheds.append(e.retry_after_s or 1.0)
                    shed_reasons.append(e.reason or "shed")
                    continue
                if e.status == 504:
                    raise DeadlineExceeded(e.detail) from None
                if e.status in (400, 404, 422):
                    raise ValueError(e.detail) from None
                # 5xx: replica-shaped failure — token-less by construction
                # (no token crossed the wire), so re-route
                self._note_peer_failure(peer, excluded, st, str(e))
                continue
            except PeerUnreachable as e:
                if (
                    getattr(e, "phase", "connect") == "read"
                    and st.timeout_retries_used < self.timeout_retries
                ):
                    # the request was already on the wire — the peer may have
                    # executed it.  Retry the SAME peer under the request's
                    # idempotency key (a dup returns the original result);
                    # re-routing here is what double-executes.
                    st.timeout_retries_used += 1
                    with self._lock:
                        self.timeout_retries_total += 1
                    self.flight.record(
                        "timeout_retry",
                        trace_id=st.trace_id,
                        peer=peer.name,
                        attempt=st.timeout_retries_used,
                        detail=str(e)[:200],
                    )
                    prefer = peer.name
                    continue
                self._note_peer_failure(peer, excluded, st, str(e))
                continue
            peer.breaker.record_success()
            peer.healthy = True
            with self._lock:
                peer.dispatched += 1
            return peer, resp

    def _note_peer_failure(
        self, peer: FleetPeer, excluded: set, st: _FleetRequest, detail: str
    ) -> None:
        """Breaker + re-route bookkeeping for a replica-shaped peer failure;
        raises when the hop budget is spent."""
        peer.breaker.record_failure()
        peer.healthy = False
        excluded.add(peer.name)
        if st.hops < self.max_reroutes:
            st.hops += 1
            with self._lock:
                self.reroutes += 1
            self.flight.record(
                "reroute",
                trace_id=st.trace_id,
                from_peer=peer.name,
                hops=st.hops,
                detail=detail[:200],
            )
            return
        with self._lock:
            self.rerouted_failed += 1
        raise EngineUnavailable(
            f"fleet request failed after {st.hops} re-routes: {detail}",
            retry_after_s=1.0,
        )

    def _maybe_pull(
        self, peer: FleetPeer, holders: Dict[str, int], st: _FleetRequest
    ) -> None:
        """Cross-process prefix pull: fetch the holder's longest matching
        entry over ``/fleet/kv/get`` and plant it in the target peer's host
        tier ahead of the dispatch — the restore path on the target is
        unchanged.  Best-effort: any failure costs one re-prefill, never
        the request."""
        src = None
        for name in sorted(holders, key=holders.get):
            if name == peer.name:
                continue
            cand = next((p for p in self.peers if p.name == name), None)
            if cand is not None and cand.healthy:
                src = cand
                break
        if src is None:
            return
        out = None
        for fetch in range(2):  # original pull + ONE integrity re-fetch
            try:
                data = src.client.post_for_bytes(
                    "/fleet/kv/get",
                    {
                        "model": self.model,
                        "prompt_ids": st.prompt_ids,
                        "prefix_len": st.prefix_len,
                    },
                    timeout_s=self.health_timeout_s * 4,
                )
                if data is None:
                    with self._lock:
                        self.pull_misses += 1
                    return
                out = peer.client.post_bytes(
                    f"/fleet/kv/put?model={urllib.parse.quote(self.model)}",
                    data,
                    timeout_s=self.health_timeout_s * 4,
                )
                break
            except PeerHTTPError as e:
                if e.reason == "wire_integrity":
                    # the payload rotted on THIS transfer — the holder still
                    # has the intact entry, so one clean re-fetch is cheap;
                    # a second corruption means cold prefill (never garbage)
                    with self._lock:
                        self.pull_integrity_rejects += 1
                    if fetch == 0:
                        with self._lock:
                            self.pull_refetches += 1
                        self.flight.record(
                            "pull_integrity_refetch",
                            trace_id=st.trace_id,
                            from_peer=src.name,
                            to_peer=peer.name,
                        )
                        continue
                with self._lock:
                    self.pull_failures += 1
                logger.warning("fleet prefix pull failed: %s", e)
                return
            except (PeerUnreachable, ValueError) as e:
                with self._lock:
                    self.pull_failures += 1
                logger.warning("fleet prefix pull failed: %s", e)
                return
        if out is None:
            return
        if out.get("stored"):
            with self._lock:
                self.prefix_pulls += 1
                self.pages_shipped += int(out.get("pages", 0))
            self.flight.record(
                "prefix_pull",
                trace_id=st.trace_id,
                from_peer=src.name,
                to_peer=peer.name,
                pages=int(out.get("pages", 0)),
            )
        else:
            with self._lock:
                self.pull_failures += 1

    # ------------------------------------------------- disaggregated handoff
    def _run_disagg(self, st: _FleetRequest) -> FleetResult:
        """Two-stage dispatch: (1) chunked prefill on the prefill pool as a
        background-class ``prefill_only`` request that pushes the finished
        prefix pages to the chosen decode peer; (2) the real request on the
        decode pool with ``prefix_len`` covering the pushed prefix, admitted
        via restore.  Greedy outputs are identical to the unified arm —
        restore bit-identity is the tested invariant underneath."""
        plen = max(st.prefix_len, len(st.prompt_ids) - 1)
        decode_cands, _ = self._candidate_order(
            st, set(), roles=("decode",)
        )
        target = next(
            (p for p in decode_cands if p.breaker.allow()), None
        )
        handoff = None
        if target is not None:
            pre_body = {
                **st.body,
                "max_tokens": 1,
                "temperature": 0.0,
                "json_format": False,
                "priority": "background",
                "prefill_only": True,
                "prefix_len": plen,
                "push_to": target.base_url,
            }
            try:
                _peer, pre = self._dispatch_loop(
                    st, pre_body, roles=("prefill",)
                )
                handoff = pre.get("handoff")
            except (EngineUnavailable, SchedulerRejected) as e:
                # the prefill pool is gone or saturated: fall back to a
                # unified dispatch (force past pool-role guards) — counted,
                # so the bench can see availability winning over purity
                with self._lock:
                    self.handoff_fallbacks += 1
                self.flight.record(
                    "handoff_fallback", trace_id=st.trace_id, detail=str(e)[:200]
                )
        if handoff is not None and handoff.get("pushed"):
            with self._lock:
                self.handoffs += 1
                self.pages_shipped += int(handoff.get("pages", 0))
            dec_body = {**st.body, "prefix_len": plen}
            peer, resp = self._dispatch_loop(
                st, dec_body, roles=("decode",), prefer=target.name
            )
            result = self._result_from(resp, peer, st)
            result.handoff = handoff
            return result
        # no usable handoff: serve anywhere (decode peers may pool_role-shed;
        # the loop's force retry keeps the request servable)
        peer, resp = self._dispatch_loop(st, st.body, roles=None)
        return self._result_from(resp, peer, st)

    def _result_from(
        self, resp: dict, peer: FleetPeer, st: _FleetRequest
    ) -> FleetResult:
        usage = resp.get("usage", {})
        return FleetResult(
            token_ids=[int(t) for t in resp.get("token_ids", [])],
            text=resp.get("result", ""),
            prompt_tokens=int(usage.get("prompt_tokens", 0)),
            completion_tokens=int(usage.get("completion_tokens", 0)),
            length_limited=bool(resp.get("length_limited", False)),
            peer=peer.name,
            reroutes=st.hops,
            trace_id=st.trace_id,
            handoff=resp.get("handoff"),
        )

    # ----------------------------------------------------------------- stats
    def stats(self) -> dict:
        with self._lock:
            peers = [
                {
                    "name": p.name,
                    "pool": p.pool,
                    "healthy": p.healthy,
                    "draining": p.draining,
                    "breaker": p.breaker.state,
                    "queued": p.queued,
                    "active": p.active,
                    "dispatched": p.dispatched,
                    "last_failure_reason": p.last_failure_reason,
                    "ttl_dropped": p.ttl_dropped,
                }
                for p in self.peers
            ]
            out = {
                "model": self.model,
                "peers_total": len(self.peers),
                "peers_healthy": sum(1 for p in self.peers if p.healthy),
                "peers": peers,
                "reroutes": self.reroutes,
                "rerouted_failed": self.rerouted_failed,
                "no_peer_available": self.no_peer_available,
                "sheds": self.sheds,
                "affinity_hits": self.affinity_hits,
                "affinity_misses": self.affinity_misses,
                "prefix_pulls": self.prefix_pulls,
                "pull_misses": self.pull_misses,
                "pull_failures": self.pull_failures,
                "pages_shipped": self.pages_shipped,
                "handoffs": self.handoffs,
                "handoff_fallbacks": self.handoff_fallbacks,
                "pool_role_bypasses": self.pool_role_bypasses,
                "refresh_failures": self.refresh_failures,
                "refresh_failure_reasons": dict(self.refresh_failure_reasons),
                "ttl_drops": self.ttl_drops,
                "gossip_digest_mismatches": self.gossip_digest_mismatches,
                "reconciles": self.reconciles,
                "reconcile_last_s": self.reconcile_last_s,
                "timeout_retries": self.timeout_retries_total,
                "pull_integrity_rejects": self.pull_integrity_rejects,
                "pull_refetches": self.pull_refetches,
            }
        out["prefix_registry"] = self.prefix_registry.stats()
        return out


# --------------------------------------------------------------- fleet plane
class FleetPlane:
    """The SERVER side of the fleet wire, one per ``serve`` process: the
    gossip delta log of local KV tier events, the KV import/export surface
    (``/fleet/kv/put|get``), the pool-role admission guard, and the
    ``/fleet/healthz`` summary.  Wired onto the registry's generators at
    construction (router event taps / engine prefix listeners); attach as
    ``registry.fleet_plane`` before ``create_app`` — the server creates a
    default unified plane when none is attached."""

    def __init__(
        self,
        registry: Any,
        *,
        name: Optional[str] = None,
        pool: Optional[str] = None,
        peers: Sequence[Tuple[str, str]] = (),
        decode_max_prefill_tokens: int = 64,
        log_size: int = 4096,
        idem_ledger_size: int = 512,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.registry = registry
        self.name = name or f"proc-{os.getpid()}"
        self.pool = pool or self._pool_from_specs(registry)
        self.peers = [(str(n), str(u)) for n, u in peers]
        self.decode_max_prefill_tokens = int(decode_max_prefill_tokens)
        self._clock = clock
        self._lock = threading.Lock()
        self._log: deque = deque(maxlen=max(16, int(log_size)))
        self._seq = 0  # seq of the NEWEST event in the log
        self._digest = 0  # rolling CRC32C chain over the WHOLE event log
        self.events_total = 0
        self.kv_puts = 0
        self.kv_gets = 0
        self.kv_put_rejects = 0
        self.kv_integrity_rejects = 0
        self.pages_in = 0
        self.pages_out = 0
        self.pushes = 0
        self.push_failures = 0
        self.pool_rejects = 0
        self.pool_bypasses = 0
        # idempotency ledger: idem_key -> (Future, done_flag).  Bounded and
        # insertion-ordered; completed entries evict first so an in-flight
        # execution is never forgotten while a dup could still arrive.
        self._idem: "OrderedDict[str, list]" = OrderedDict()
        self._idem_cap = max(8, int(idem_ledger_size))
        self.idem_executions = 0
        self.idem_hits = 0
        self.idem_coalesced = 0
        self.idem_evictions = 0
        self._wire(registry)

    @staticmethod
    def _pool_from_specs(registry: Any) -> str:
        for spec in getattr(registry, "specs", {}).values():
            pool = getattr(spec, "pool", "unified")
            if getattr(spec, "kind", "") == "decoder" and pool != "unified":
                return pool
        return "unified"

    def _wire(self, registry: Any) -> None:
        """Chain onto every generator's tier-event plumbing: routers get an
        event tap (their replicas' listeners stay registry-owned), bare
        engines get the prefix listener directly.  Defensive throughout —
        an odd test registry must never break plane construction."""
        for model, eng in getattr(registry, "generators", {}).items():
            try:
                tap = getattr(eng, "set_event_tap", None)
                if callable(tap):
                    tap(
                        lambda replica, event, key, length, _m=model: (
                            self.on_tier_event(_m, replica, event, key, length)
                        )
                    )
                    continue
                setter = getattr(eng, "set_prefix_listener", None)
                if callable(setter):
                    rep_name = getattr(eng, "name", model)
                    setter(
                        lambda event, key, length, pages, _m=model, _n=rep_name: (
                            self.on_tier_event(_m, _n, event, key, length)
                        )
                    )
            except Exception:
                logger.exception("fleet plane wiring failed for %s", model)

    # ---------------------------------------------------------------- gossip
    def on_tier_event(
        self, model: str, replica: str, event: str, key: tuple, length: int
    ) -> None:
        ev = {
            "model": model,
            "replica": replica,
            "event": event,
            "key": [int(t) for t in key],
            "length": int(length),
        }
        with self._lock:
            self._seq += 1
            self.events_total += 1
            self._log.append((self._seq, ev))
            self._digest = _chain_digest(self._digest, ev)

    def prefix_events(self, since: int) -> dict:
        """Delta log entries past ``since``; when the cursor predates the
        log window (trim or process restart), a ``reset`` with the full
        warm-holdings snapshot instead — followers drop-and-reapply.  Both
        shapes carry the log's rolling ``digest`` so a follower whose own
        chain diverges (missed delta, disagreeing builds) can detect it and
        force this reset path instead of silently skewing affinity."""
        with self._lock:
            seq = self._seq
            digest = self._digest
            oldest = self._log[0][0] if self._log else self._seq + 1
            if since >= oldest - 1:
                events = [ev for s, ev in self._log if s > since]
                return {"seq": seq, "digest": digest, "events": events}
        return {
            "seq": seq,
            "digest": digest,
            "reset": True,
            "holdings": self._holdings(),
        }

    # ----------------------------------------------------- idempotent dispatch
    def idem_claim(self, key: str) -> Tuple[str, Future]:
        """Claim an idempotency key.  ``("mine", fut)`` means the caller owns
        the execution and must later :meth:`idem_complete` (success) or
        :meth:`idem_release` (failure) the SAME future; ``("wait", fut)``
        means another execution owns it — await the future, a non-``None``
        result is the original response to return verbatim."""
        with self._lock:
            rec = self._idem.get(key)
            if rec is not None:
                if rec[1]:
                    self.idem_hits += 1
                else:
                    self.idem_coalesced += 1
                return ("wait", rec[0])
            fut: Future = Future()
            self._idem[key] = [fut, False]
            self.idem_executions += 1
            while len(self._idem) > self._idem_cap:
                victim = next(
                    (k for k, r in self._idem.items() if r[1]), None
                ) or next(iter(self._idem))
                del self._idem[victim]
                self.idem_evictions += 1
            return ("mine", fut)

    def idem_complete(self, key: str, fut: Future, payload: dict) -> None:
        """Record a successful execution: dups arriving later (or already
        awaiting) get ``payload`` back instead of a re-execution."""
        with self._lock:
            rec = self._idem.get(key)
            if rec is not None and rec[0] is fut:
                rec[1] = True
        # resolve OUTSIDE the lock — waiter callbacks run inline (DABT102)
        if not fut.done():
            fut.set_result(payload)

    def idem_release(self, key: str, fut: Future) -> None:
        """Failed execution: drop the ledger entry so a retry re-executes,
        and resolve waiters with ``None`` (their cue to claim afresh)."""
        with self._lock:
            rec = self._idem.get(key)
            if rec is not None and rec[0] is fut:
                del self._idem[key]
        if not fut.done():
            fut.set_result(None)

    def _holdings(self) -> List[dict]:
        """Warm holdings across every generator's HOST tier (host DRAM +
        disk — the durable tiers; write-through keeps registered HBM
        prefixes mirrored there, so for routing purposes this IS the warm
        set).  ``length == len(key)`` by construction of prefix keys."""
        out: List[dict] = []
        for model, eng in getattr(self.registry, "generators", {}).items():
            reps = getattr(eng, "replicas", None)
            pairs = (
                [(rep.name, rep.engine) for rep in reps]
                if reps is not None
                else [(getattr(eng, "name", model), eng)]
            )
            for rep_name, e in pairs:
                tier = getattr(e, "kv_host_tier", None)
                if tier is None:
                    continue
                try:
                    for key, _pages in tier.warm_keys():
                        out.append(
                            {
                                "model": model,
                                "replica": rep_name,
                                "key": [int(t) for t in key],
                                "length": len(key),
                                "tier": TIER_HOST,
                            }
                        )
                except Exception:
                    logger.exception("fleet holdings snapshot failed")
        return out

    # ------------------------------------------------------------ KV surface
    def _model_engines(self, model: str) -> List[Any]:
        eng = self.registry.get_generator(model)
        if eng is None:
            raise KeyError(model)
        reps = getattr(eng, "replicas", None)
        if reps is not None:
            return [rep.engine for rep in reps]
        return [eng]

    def kv_get_wire(
        self, model: str, prompt_ids: Sequence[int], prefix_len: int
    ) -> Optional[bytes]:
        """Longest matching warm prefix across this process's replicas,
        wire-encoded; None on a miss.  Read-only on every tier."""
        best: Optional[HostPrefixEntry] = None
        for eng in self._model_engines(model):
            tier = getattr(eng, "kv_host_tier", None)
            if tier is None:
                continue
            ent = tier.export_match(prompt_ids, prefix_len)
            if ent is not None and (best is None or ent.length > best.length):
                best = ent
        if best is None:
            return None
        with self._lock:
            self.kv_gets += 1
            self.pages_out += int(best.pages)
        return encode_kv_entry(best)

    def kv_put_wire(self, model: str, data: bytes) -> dict:
        """Decode + absorb one wire entry into the least-loaded replica's
        host tier (geometry/dtype validated by the engine).  Raises
        :class:`WireVersionError` for cross-build payloads,
        :class:`WireIntegrityError` for checksum-failed ones (counted —
        the chaos bench's rejected-corruption criterion reads it here),
        ``ValueError`` for malformed ones, ``KeyError`` for an unknown
        model."""
        try:
            entry = decode_kv_entry(data)
        except WireIntegrityError:
            with self._lock:
                self.kv_integrity_rejects += 1
            raise
        engines = self._model_engines(model)
        engines.sort(key=lambda e: e.queued_depth() + e.num_active)
        stored = False
        pages = 0
        for eng in engines:
            absorb = getattr(eng, "absorb_remote_entry", None)
            if not callable(absorb):
                continue
            if absorb(entry.key, entry.length, entry.k, entry.v):
                stored = True
                tier = eng.kv_host_tier
                page = getattr(tier, "page_size", 1)
                pages = -(-entry.length // max(1, page))
                break
        with self._lock:
            if stored:
                self.kv_puts += 1
                self.pages_in += pages
            else:
                self.kv_put_rejects += 1
        return {"stored": stored, "pages": pages, "key_tokens": len(entry.key)}

    def handoff_export(
        self,
        model: str,
        prompt_ids: Sequence[int],
        prefix_len: int,
        push_to: Optional[str],
    ) -> dict:
        """The prefill-pool epilogue: export the just-registered prefix
        entry (write-through already mirrored it to the host tier; a cheap
        spill sweep covers the writethrough-off case) and push it to the
        decode peer named by ``push_to``.  Best-effort — a failed push
        degrades to the decode peer pulling or re-prefilling."""
        plen = max(0, min(int(prefix_len), len(prompt_ids) - 1))
        key = tuple(int(t) for t in prompt_ids[:plen])
        entry = None
        engines = self._model_engines(model)
        for attempt in range(2):
            for eng in engines:
                tier = getattr(eng, "kv_host_tier", None)
                if tier is None:
                    continue
                entry = tier.export_entry(key)
                if entry is not None:
                    break
            if entry is not None or attempt == 1:
                break
            for eng in engines:
                spill = getattr(eng, "spill_registered_to_host", None)
                if callable(spill):
                    try:
                        spill()
                    except Exception:
                        logger.exception("handoff spill sweep failed")
        if entry is None:
            return {
                "key_tokens": plen,
                "length": plen,
                "pages": 0,
                "pushed": False,
                "reason": "no_entry",
            }
        out = {
            "key_tokens": len(entry.key),
            "length": int(entry.length),
            "pages": int(entry.pages),
            "pushed": False,
        }
        if push_to:
            scheme = urllib.parse.urlsplit(push_to).scheme
            if scheme not in ("http", "https"):
                out["reason"] = "bad_push_to"
                return out
            try:
                resp = PeerClient(push_to, timeout_s=20.0).post_bytes(
                    f"/fleet/kv/put?model={urllib.parse.quote(model)}",
                    encode_kv_entry(entry),
                )
            except (PeerUnreachable, PeerHTTPError, ValueError) as e:
                with self._lock:
                    self.push_failures += 1
                out["reason"] = f"push_failed: {e}"[:200]
                return out
            out["pushed"] = bool(resp.get("stored"))
            with self._lock:
                if out["pushed"]:
                    self.pushes += 1
                    self.pages_out += int(entry.pages)
                else:
                    self.push_failures += 1
        return out

    # -------------------------------------------------------- admission guard
    def admission_guard(
        self,
        model: str,
        eng: Any,
        prompt_ids: Sequence[int],
        prefix_len: int,
        *,
        prefill_only: bool,
        force: bool,
    ) -> Optional[SchedulerRejected]:
        """The pool-role contract at /fleet/generate admission: a prefill
        process serves only ``prefill_only`` work; a decode process never
        runs long prefill — a request whose un-restorable suffix exceeds
        ``decode_max_prefill_tokens`` sheds with reason ``pool_role`` so the
        FleetRouter hands it off instead.  ``force`` bypasses (counted):
        when a whole pool is dead, availability beats purity."""
        pool = self.pool
        if pool == "unified":
            return None
        if force:
            with self._lock:
                self.pool_bypasses += 1
            return None
        if pool == "prefill" and not prefill_only:
            with self._lock:
                self.pool_rejects += 1
            return SchedulerRejected("pool_role", 1.0)
        if pool == "decode":
            if prefill_only:
                with self._lock:
                    self.pool_rejects += 1
                return SchedulerRejected("pool_role", 1.0)
            warm = self._holds(eng, prompt_ids, prefix_len)
            suffix = len(prompt_ids) - (prefix_len if warm else 0)
            if suffix > self.decode_max_prefill_tokens:
                with self._lock:
                    self.pool_rejects += 1
                return SchedulerRejected("pool_role", 1.0)
        return None

    @staticmethod
    def _holds(eng: Any, prompt_ids: Sequence[int], prefix_len: int) -> bool:
        reps = getattr(eng, "replicas", None)
        engines = [rep.engine for rep in reps] if reps is not None else [eng]
        for e in engines:
            fn = getattr(e, "holds_prefix", None)
            if callable(fn):
                try:
                    if fn(prompt_ids, prefix_len):
                        return True
                except Exception:
                    continue
        return False

    # ----------------------------------------------------------- healthz etc
    def healthz(self, *, check_peers: bool = False) -> dict:
        """The /fleet/healthz body: per-model supervision/load/latency/
        breaker summary plus the fleet block (pool role, gossip seq, peer
        reachability).  ``check_peers`` probes each configured peer's
        /healthz with a short timeout — the fleet status degrades when a
        peer is gone, which is exactly what the chaos smoke asserts."""
        reg = self.registry
        status = "ok"
        models: Dict[str, Any] = {}
        queued_total = 0
        active_total = 0
        for name, eng in getattr(reg, "generators", {}).items():
            m: Dict[str, Any] = {}
            try:
                m["queued"] = int(eng.queued_depth())
                m["active"] = int(eng.num_active)
            except Exception:
                m["queued"] = m["active"] = 0
            queued_total += m["queued"]
            active_total += m["active"]
            healthy_fn = getattr(eng, "healthy", None)
            if callable(healthy_fn):
                try:
                    m["healthy"] = bool(healthy_fn())
                except Exception:
                    m["healthy"] = False
                if not m["healthy"]:
                    status = "degraded"
            lat = getattr(eng, "latency_stats", None)
            if callable(lat):
                try:
                    m["latency"] = lat()
                except Exception:
                    pass
            rs = getattr(eng, "router_stats", None)
            if callable(rs):
                try:
                    r = rs()
                    m["replicas"] = [
                        {
                            "name": rep["name"],
                            "breaker": rep["breaker"],
                            "draining": rep["draining"],
                        }
                        for rep in r.get("replicas", [])
                    ]
                    for k in ("slices_total", "slices_free"):
                        if k in r:
                            m[k] = r[k]
                except Exception:
                    pass
            models[name] = m
        with self._lock:
            seq = self._seq
            digest = self._digest
            integrity_rejects = self.kv_integrity_rejects
            idem_hits = self.idem_hits
        out = {
            "status": status,
            "name": self.name,
            "load": {"queued": queued_total, "active": active_total},
            "models": models,
            "fleet": {
                "pool": self.pool,
                "seq": seq,
                "digest": digest,
                "peers_total": len(self.peers),
                "kv_integrity_rejects": integrity_rejects,
                "idem_hits": idem_hits,
            },
        }
        if check_peers and self.peers:
            reachable = 0
            peer_rows = []
            for pname, url in self.peers:
                ok = True
                try:
                    PeerClient(url, timeout_s=2.0).get_json("/healthz")
                except (PeerUnreachable, PeerHTTPError, ValueError):
                    ok = False
                reachable += 1 if ok else 0
                peer_rows.append({"name": pname, "url": url, "reachable": ok})
            out["fleet"]["peers_reachable"] = reachable
            out["fleet"]["peers"] = peer_rows
            out["fleet"]["status"] = (
                "ok" if reachable == len(self.peers) else "degraded"
            )
        return out

    def collect_traces(self) -> List[dict]:
        """Every generator's obs trace ring, flattened — the GET /traces
        body the trace-export CLI consumes (cli/trace_export.py)."""
        out: List[dict] = []
        for _model, eng in getattr(self.registry, "generators", {}).items():
            reps = getattr(eng, "replicas", None)
            engines = [rep.engine for rep in reps] if reps is not None else [eng]
            for e in engines:
                obs = getattr(e, "obs", None)
                if obs is not None:
                    try:
                        out.extend(obs.traces())
                    except Exception:
                        logger.exception("trace collection failed")
        return out

    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "pool": self.pool,
                "peers_total": len(self.peers),
                "gossip_seq": self._seq,
                "gossip_digest": self._digest,
                "gossip_events_total": self.events_total,
                "kv_puts": self.kv_puts,
                "kv_gets": self.kv_gets,
                "kv_put_rejects": self.kv_put_rejects,
                "kv_integrity_rejects": self.kv_integrity_rejects,
                "idem_executions": self.idem_executions,
                "idem_hits": self.idem_hits,
                "idem_coalesced": self.idem_coalesced,
                "idem_evictions": self.idem_evictions,
                "idem_ledger": len(self._idem),
                "pages_in": self.pages_in,
                "pages_out": self.pages_out,
                "pushes": self.pushes,
                "push_failures": self.push_failures,
                "pool_rejects": self.pool_rejects,
                "pool_bypasses": self.pool_bypasses,
            }
