"""TPU serving plane — the replacement for the reference's ``gpu_service``.

The reference serves models from a FastAPI app with per-gunicorn-worker torch model
replicas, an unbatched embedding loop, and single-stream ``generate``
(reference: gpu_service/main.py:52-107, gpu_service/gunicorn_conf.py:9-16,
assistant/ai/embedders/transformers.py:15-29 — SURVEY.md §3.3 calls out both
deficiencies).  This plane is one process driving the whole TPU slice:

- :mod:`.tokenizer` — HF tokenizer wrapper + byte-level fallback, chat templating;
- :mod:`.engine`    — continuous-batching generation engine (paged block-table
  KV cache by default, bucketed prefill, jit decode tick) and a coalescing
  batched embedding engine;
- :mod:`.kv_pool`   — host-side page allocator for the paged KV plane
  (refcounted prefix sharing, COW, LRU byte budget — docs/KV_PAGING.md);
- :mod:`.streaming` — per-request token streams + UTF-8-safe incremental
  detokenization (``GenerationEngine.generate_stream`` and the SSE wire);
- :mod:`.scheduler` — admission-controlled request scheduler (priority classes,
  weighted per-tenant fair share, deadlines, bounded queue + load shedding);
- :mod:`.faults`    — deterministic seeded fault injection (the chaos plane
  that exercises the engine's quarantine/restart/circuit recovery paths);
- :mod:`.router`    — fault-tolerant multi-replica front door: health- and
  prefix-affinity-aware dispatch over N supervised engine replicas with
  per-replica circuit breakers, token-less re-route, graceful drain, and a
  dynamic fleet surface (``add_replica``/``remove_replica``);
- :mod:`.autoscaler` — the SLO-driven control loop over the obs plane's
  signals: replica count, predictive admission, and load-adaptive
  degradation actuated from p95 TTFT burn / shed rate / queue backlog / KV
  pressure (docs/AUTOSCALING.md);
- :mod:`.obs`       — serving-plane observability: per-request span traces
  (``X-Request-Id`` end to end), Prometheus ``/metrics`` histograms, and the
  crash flight recorder the failure paths dump (docs/OBSERVABILITY.md);
- :mod:`.registry`  — model registry loading checkpoints onto the mesh;
- :mod:`.server`    — aiohttp app exposing the reference's exact HTTP contract
  (``POST /embeddings/``, ``POST /dialog/``) plus SSE streaming, ``/healthz``
  and ``GET /metrics``.
"""

from .tokenizer import ByteTokenizer, Tokenizer, load_tokenizer  # noqa: F401
from .engine import (  # noqa: F401
    EmbeddingEngine,
    EngineUnavailable,
    GenerationEngine,
    GenerationResult,
    RequestPoisoned,
)
from .faults import FaultInjected, FaultInjector  # noqa: F401
from .obs import (  # noqa: F401
    EngineObs,
    FlightRecorder,
    Histogram,
    new_trace_id,
    parse_prometheus_text,
    render_prometheus,
    setup_json_logging,
)
from .streaming import (  # noqa: F401
    IncrementalDetokenizer,
    StreamChunk,
    TokenStream,
)
from .scheduler import (  # noqa: F401
    DeadlineExceeded,
    RequestScheduler,
    SchedulerConfig,
    SchedulerRejected,
)
from .router import EngineRouter  # noqa: F401
from .autoscaler import AutoscalerConfig, SLOAutoscaler  # noqa: F401
from .registry import ModelRegistry, ModelSpec  # noqa: F401
