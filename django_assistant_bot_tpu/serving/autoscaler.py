"""SLO-driven autoscaler: the control loop that closes the telemetry plane.

PR 10 gave the fleet real signals (TTFT/queue-wait histograms, shed counters,
KV gauges, per-replica health — serving/obs.py); ROADMAP item 6 named the gap:
nothing *consumed* them.  :class:`SLOAutoscaler` is the consumer — a small
controller thread that scrapes the fleet's own stats surfaces and actuates
through the router's existing primitives (docs/AUTOSCALING.md):

- **Signals** (read every ``interval_s``; no locks held across any of them):
  p95 TTFT against the SLO (*burn* = observed/target), admission shed RATE
  (delta of the schedulers' shed counters over the control interval),
  queue-wait backlog (the schedulers' predicted wait — histogram-quantile
  floored, serving/scheduler.py), and KV page-pool occupancy.
- **Actuators**, cheapest first: *degradation* (force every replica's
  scheduler degrade band on: max_tokens clamp + speculative decode off),
  *scale-up* (``router.add_replica()`` — a fresh replica from the shared
  ModelSpec weights), *scale-down* (``router.remove_replica()`` —
  drain-then-detach, zero-shed by construction; chaos-verified against the
  replica dying mid-drain, the exact race the flight recorder and lock
  witness exist to catch).
- **Flap prevention**: scale-up needs ``up_consecutive`` overloaded control
  ticks, scale-down ``down_consecutive`` trough ticks (*all* signals calm, a
  one-replica-smaller fleet projected to hold, zero sheds in the window);
  each direction then starts its own cooldown.  Bounds
  ``[min_replicas, max_replicas]`` are hard.

Clock discipline (dabtlint DABT105): every timestamp flows through the
injectable ``clock``/``sleep``, so the whole decision suite runs under a fake
clock — scale-up on SLO burn, trough scale-down, hysteresis under an
oscillating trace — with zero sleep-and-hope.  Every decision lands in the
autoscaler's own flight-recorder ring (dumped alongside engine artifacts) and
as ``dabt_autoscale_*`` metrics on ``/metrics``.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, Optional

from .obs import FlightRecorder

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class AutoscalerConfig:
    # fleet bounds (hard): ModelSpec.replicas is the initial/min size,
    # ModelSpec.max_replicas the ceiling (serving/registry.py)
    min_replicas: int = 1
    max_replicas: int = 1
    # control period: signals are deltas/levels over this window
    interval_s: float = 1.0
    # the SLO this controller defends: p95 time-to-first-token
    slo_ttft_p95_s: float = 1.0
    # ---- scale-up triggers (ANY fires the overload band) -------------------
    up_burn: float = 1.0  # p95 TTFT / SLO at or past this
    up_shed_per_s: float = 0.5  # admission sheds per second over the window
    up_est_wait_frac: float = 0.5  # predicted queue wait / SLO
    up_kv_frac: float = 0.9  # KV pages used / total
    # decode-pool signal (docs/FLEET.md): p95 inter-token latency at or past
    # this fires the overload band.  None (default) keeps the controller
    # TTFT-driven — the right signal for unified and prefill pools, where
    # admission latency IS the SLO; a decode pool's latency is ITL.
    up_itl_p95_s: Optional[float] = None
    up_consecutive: int = 2  # overloaded ticks before actuating (hysteresis)
    up_cooldown_s: float = 5.0
    # ---- scale-down triggers (ALL must hold for the trough band) -----------
    down_burn: float = 0.5
    down_est_wait_frac: float = 0.1
    down_kv_frac: float = 0.5
    # a one-replica-smaller fleet must be projected to hold the current load:
    # (queued + active) / (slots * (n-1)) <= this utilization
    down_util: float = 0.5
    down_consecutive: int = 3
    down_cooldown_s: float = 30.0
    # scale-down drain budget (remove_replica deadline)
    drain_deadline_s: float = 30.0
    # ---- load-adaptive degradation (cheaper than a replica) ----------------
    # engage when burn crosses degrade_burn while the overload band holds (or
    # the fleet is already at max); release when burn falls below
    # degrade_release_burn AND the overload band has cleared — two thresholds,
    # so the band cannot chatter around one line
    degrade_burn: float = 1.5
    degrade_release_burn: float = 0.75
    degrade_max_tokens: int = 256

    def validate(self) -> "AutoscalerConfig":
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        if self.slo_ttft_p95_s <= 0:
            raise ValueError("slo_ttft_p95_s must be > 0")
        if self.degrade_release_burn >= self.degrade_burn:
            raise ValueError(
                "degrade_release_burn must be < degrade_burn (hysteresis)"
            )
        return self


class SLOAutoscaler:
    """One controller per :class:`~.router.EngineRouter`.

    ``tick()`` is the whole policy — one signal read, one decision, at most
    one actuation — and is public so the deterministic test suite drives it
    directly under a fake clock; :meth:`start` just runs it on a daemon
    thread every ``interval_s``.
    """

    def __init__(
        self,
        router,
        cfg: AutoscalerConfig,
        *,
        name: str = "autoscaler",
        clock: Callable[[], float] = time.monotonic,
        sleep: Optional[Callable[[float], None]] = None,
    ):
        self.router = router
        self.cfg = cfg.validate()
        self.name = name
        self._clock = clock
        # tests inject sleep; the thread otherwise waits on the stop event so
        # stop() interrupts an interval instead of riding it out
        self._sleep = sleep
        self.flight = FlightRecorder(name=name, clock=clock)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()  # guards the counters below only
        # controller state (single control thread; counters read by scrapes)
        self._last_at: Optional[float] = None
        # per-replica shed totals by replica NAME (names are never reused —
        # the router's spawn counter is monotonic), so the per-window shed
        # delta stays monotone across scale-downs: summing only the live
        # fleet would go NEGATIVE when a replica detaches with history,
        # masking real sheds in exactly the interval load got redistributed
        self._shed_seen: dict = {}
        self._shed_primed = False
        self._up_ticks = 0
        self._down_ticks = 0
        self._up_ok_at = 0.0  # cooldown expiry stamps (clock domain)
        self._down_ok_at = 0.0
        self.degrade_active = False
        # decision counters (the dabt_autoscale_* metric surface)
        self.ticks = 0
        self.scale_ups = 0
        self.scale_downs = 0
        self.scale_up_failures = 0
        # WHY a wanted scale-up did not happen, by reason — so an operator
        # can tell "at hardware limit" (no_capacity: the mesh planner has no
        # free device slice, docs/MULTICHIP.md) from "flap-damped"
        # (cooldown) and "at the configured ceiling" (bounds).  Counted every
        # overloaded tick the actuator was held back; the flight ring gets
        # one event per reason TRANSITION, not per tick.
        self.scale_up_skipped: dict = {
            "cooldown": 0,
            "bounds": 0,
            "no_capacity": 0,
        }
        self.last_skip_reason: Optional[str] = None
        # sticky until a scale event changes capacity: while set, the
        # overload band engages degradation exactly as at the configured
        # ceiling — shaping load is the only actuator left at the hardware
        # limit
        self._no_capacity = False
        # warm-state durability: what scale-downs preserved vs dropped
        self.warm_entries_migrated = 0
        self.warm_pages_migrated = 0
        self.warm_pages_lost = 0
        self.degrade_engaged = 0
        self.degrade_released = 0
        # integral of fleet size over time — the cost axis of the bench A/B
        # (replica-seconds: what a fixed max-size fleet pays all the time)
        self.replica_seconds = 0.0
        self.last_signals: dict = {}
        self.last_decision: str = "init"

    # ------------------------------------------------------------- signals
    def _signals(self) -> dict:
        """One scrape of the fleet's own stats surfaces.  Each surface does
        its own locking; nothing here holds one component's lock across
        another's call (the PR 7 ABBA family the witness convicts)."""
        router = self.router
        lat = router.latency_stats()
        ttft_p95_s = float(lat.get("ttft_p95_ms", 0.0)) / 1e3
        itl_p95_s = float(lat.get("itl_p95_ms", 0.0)) / 1e3
        shed_total = 0
        shed_delta = 0
        seen: dict = {}
        est_wait_s = 0.0
        queued = 0
        active = 0
        slots = 0
        for rep in list(router.replicas):
            eng = rep.engine
            queued += eng.queued_depth()
            active += eng.num_active
            slots += getattr(eng, "max_slots", 0)
            sched = getattr(eng, "scheduler", None)
            if sched is not None:
                st = sched.stats()
                total = sum(st.get("shed", {}).values())
                shed_total += total
                name = getattr(rep, "name", str(id(rep)))
                seen[name] = total
                shed_delta += max(0, total - self._shed_seen.get(name, 0))
                est_wait_s = max(est_wait_s, float(st.get("est_wait_s", 0.0)))
        if not self._shed_primed:
            # first scrape: pre-existing counters are history, not a window
            shed_delta = 0
            self._shed_primed = True
        self._shed_seen = seen
        kv = router.kv_stats()
        kv_total = kv.get("kv_pages_total", 0)
        if kv_total:
            # pressure = pages a new request could NOT obtain: evictable
            # cached-prefix pages don't count (a warm prefix cache is not
            # load, and must not pin the overload band / block the trough)
            obtainable = kv.get(
                "kv_pages_obtainable",
                kv_total - kv.get("kv_pages_used", 0),
            )
            kv_frac = 1.0 - obtainable / kv_total
        else:
            kv_frac = 0.0
        return {
            "replicas": len(router.replicas),
            "ttft_p95_s": round(ttft_p95_s, 4),
            "itl_p95_s": round(itl_p95_s, 4),
            "ttft_n": lat.get("ttft_n", 0),
            "shed_total": shed_total,
            "shed_delta": shed_delta,
            "est_wait_s": round(est_wait_s, 4),
            "kv_frac": round(kv_frac, 4),
            "queued": queued,
            "active": active,
            "slots": slots,
        }

    # ------------------------------------------------------------- the loop
    def tick(self) -> dict:
        """One control iteration: read signals, classify the band, actuate at
        most once.  Returns the decision record (also appended to the flight
        ring) — the deterministic test surface."""
        cfg = self.cfg
        now = self._clock()
        dt = 0.0 if self._last_at is None else max(0.0, now - self._last_at)
        self._last_at = now
        sig = self._signals()
        n = sig["replicas"]
        with self._lock:
            # the integral is also closed by stop(), possibly while a zombie
            # tick is mid-drain — both sites go through the lock
            self.replica_seconds += n * dt
        shed_delta = sig["shed_delta"]
        shed_rate = shed_delta / dt if dt > 0 else float(shed_delta)
        burn = sig["ttft_p95_s"] / cfg.slo_ttft_p95_s
        sig.update(
            shed_rate=round(shed_rate, 4),
            burn=round(burn, 4),
        )

        # the TTFT p95 comes from the engines' ROLLING sample window: after
        # traffic stops, the window keeps reporting the last spike forever.
        # Burn is evidence only while work is actually in flight — an idle
        # fleet with a scary stale p95 must neither hold the overload band
        # nor be blocked from scaling down / releasing degradation.
        busy = (sig["queued"] + sig["active"]) > 0
        sig["busy"] = busy
        # decode pools scale on ITL, not TTFT (docs/FLEET.md): the same
        # busy-gating applies — a stale rolling window must not hold the band
        itl_hot = (
            cfg.up_itl_p95_s is not None
            and busy
            and sig["itl_p95_s"] >= cfg.up_itl_p95_s
        )
        overload = (
            (busy and burn >= cfg.up_burn)
            or itl_hot
            or shed_rate >= cfg.up_shed_per_s
            or sig["est_wait_s"] >= cfg.up_est_wait_frac * cfg.slo_ttft_p95_s
            or sig["kv_frac"] >= cfg.up_kv_frac
        )
        burn_calm = not busy or burn <= cfg.down_burn
        itl_calm = (
            cfg.up_itl_p95_s is None
            or not busy
            or sig["itl_p95_s"] <= 0.5 * cfg.up_itl_p95_s
        )
        burn_released = not busy or burn <= cfg.degrade_release_burn
        # projected utilization of a ONE-SMALLER fleet: scale-down must not
        # immediately re-trigger scale-up (the flap the bands exist to stop)
        smaller_slots = max(1, sig["slots"] - sig["slots"] // max(1, n))
        shrunk_util = (sig["queued"] + sig["active"]) / smaller_slots
        trough = (
            not overload
            and burn_calm
            and itl_calm
            and shed_delta == 0
            and sig["est_wait_s"] <= cfg.down_est_wait_frac * cfg.slo_ttft_p95_s
            and sig["kv_frac"] <= cfg.down_kv_frac
            and shrunk_util <= cfg.down_util
        )
        if overload:
            self._up_ticks += 1
            self._down_ticks = 0
        elif trough:
            self._down_ticks += 1
            self._up_ticks = 0
        else:
            self._up_ticks = 0
            self._down_ticks = 0

        decision = "hold"
        if overload and self._up_ticks >= cfg.up_consecutive:
            if n < cfg.max_replicas and now >= self._up_ok_at:
                decision = self._scale_up(now)
                if decision == "no_capacity" and not self.degrade_active:
                    # the refused spawn was not an actuation: at the
                    # hardware limit fall through to degradation on the
                    # SAME tick, exactly as the max_replicas branch —
                    # otherwise a short cooldown could turn every
                    # qualifying tick into another refused probe and load
                    # shaping would never engage on a saturated host
                    self._set_degrade(True)
                    decision = "no_capacity+degrade_on"
            else:
                # a scale-up was WANTED and held back — record why, so "at
                # hardware limit" is distinguishable from bounds/cooldown on
                # the stats surface.  While the no-capacity flag is sticky
                # (nothing freed a slice since the last refused attempt) the
                # cooldown is incidental — the holdback IS the hardware
                # limit, and attributing it to "cooldown" would read as
                # flap-damping on a saturated host.
                if n >= cfg.max_replicas:
                    reason = "bounds"
                elif self._no_capacity:
                    reason = "no_capacity"
                else:
                    reason = "cooldown"
                self._note_skip(reason, sig)
                if burn >= cfg.degrade_burn and not self.degrade_active:
                    decision = self._set_degrade(True)
                elif (
                    n >= cfg.max_replicas or self._no_capacity
                ) and not self.degrade_active:
                    # at the ceiling — configured (max_replicas) or hardware
                    # (no free device slice) — with the overload band held:
                    # shaping load is the only actuator left, whatever the
                    # burn level
                    decision = self._set_degrade(True)
        elif trough and self._down_ticks >= cfg.down_consecutive:
            if self.degrade_active and burn_released:
                decision = self._set_degrade(False)
            elif n > cfg.min_replicas and now >= self._down_ok_at:
                decision = self._scale_down(now)
        elif self.degrade_active and not overload and burn_released:
            decision = self._set_degrade(False)

        with self._lock:
            self.ticks += 1
            self.last_signals = sig
            self.last_decision = decision
        record = {"decision": decision, **sig}
        if decision != "hold":
            self.flight.record("autoscale", **record)
        return record

    # ----------------------------------------------------------- actuators
    def _note_skip(
        self, reason: str, sig: Optional[dict] = None, *, record: bool = True
    ) -> bool:
        """Count a held-back scale-up by reason; flight-record only on a
        reason TRANSITION (the counters carry the per-tick evidence — one
        ring event per band entry keeps the crash artifact readable).
        Returns whether the reason changed."""
        sig = sig or {}
        with self._lock:
            self.scale_up_skipped[reason] = (
                self.scale_up_skipped.get(reason, 0) + 1
            )
            changed = self.last_skip_reason != reason
            self.last_skip_reason = reason
        if record and changed:
            self.flight.record(
                "scale_up_skipped",
                reason=reason,
                replicas=sig.get("replicas"),
                burn=sig.get("burn"),
                shed_rate=sig.get("shed_rate"),
            )
        return changed

    def _scale_up(self, now: float) -> str:
        try:
            name = self.router.add_replica()
        except Exception as e:
            from ..parallel.slicing import NoCapacity

            if isinstance(e, NoCapacity):
                # slices exhausted: an HONEST "at hardware limit" decision,
                # distinct from a failed spawn — the fleet holds its size, no
                # same-chip cache clone is ever created, and the overload
                # band falls through to degradation on later ticks.  The
                # cooldown still applies so a saturated host is not probed
                # every control tick; a scale-down frees a slice and clears
                # the sticky flag.  The shared skip ledger counts the tick;
                # the richer event rides the ring only on the LIMIT
                # TRANSITION (repeat refusals are counter evidence, not ring
                # spam).
                first = not self._no_capacity
                self._no_capacity = True
                self._note_skip("no_capacity", record=False)
                # cooldown, but NO _up_ticks reset: a refusal is not an
                # actuation — the overload band stays armed so degradation
                # (tick()'s fall-through) engages immediately instead of
                # waiting out a fresh hysteresis window per refused probe
                self._up_ok_at = now + self.cfg.up_cooldown_s
                if first:
                    self.flight.record(
                        "scale_up_no_capacity",
                        reason="no_capacity",
                        slices_total=getattr(e, "slices_total", 0),
                        replica_devices=getattr(e, "replica_devices", 0),
                        error=str(e),
                    )
                logger.warning("autoscaler: scale-up skipped — %s", e)
                return "no_capacity"
            # a failed spawn (OOM, factory error) must not kill the control
            # loop: count it, leave the cooldown untouched so the next tick
            # can retry
            logger.exception("autoscaler: scale-up failed")
            with self._lock:
                self.scale_up_failures += 1
            self.flight.record("scale_up_failed", error=f"{type(e).__name__}: {e}")
            return "scale_up_failed"
        with self._lock:
            self.scale_ups += 1
            self.last_skip_reason = None
        self._no_capacity = False
        self._up_ok_at = now + self.cfg.up_cooldown_s
        self._up_ticks = 0
        if self.degrade_active:
            # the new replica must degrade with the rest of the fleet until
            # the band releases
            self._apply_degrade(True)
        logger.info("autoscaler: scaled up (+%s)", name)
        return "scale_up"

    def _pick_victim(self) -> Optional[int]:
        """Least-loaded non-draining replica's CURRENT index (resolved at
        call time; remove_replica re-checks under its own lock)."""
        reps = list(self.router.replicas)
        best = None
        for i, rep in enumerate(reps):
            if rep.draining:
                continue
            load = rep.engine.queued_depth() + rep.engine.num_active
            if best is None or load < best[0]:
                best = (load, i)
        return best[1] if best is not None else None

    def _scale_down(self, now: float) -> str:
        victim = self._pick_victim()
        if victim is None:
            return "hold"
        try:
            report = self.router.remove_replica(
                victim, deadline_s=self.cfg.drain_deadline_s
            )
        except RuntimeError as e:
            # lost the race with a concurrent drain/removal — not a failure
            self.flight.record("scale_down_skipped", error=str(e))
            return "hold"
        with self._lock:
            self.scale_downs += 1
            # a detach released capacity (on a sliced fleet, a device slice):
            # the next wanted scale-up gets a fresh verdict
            self._no_capacity = False
            # warm-state durability accounting (docs/KV_PAGING.md "Tiered
            # KV"): a scale-down is no longer a silent cache wipe — the
            # migration result rides in the detach report, accumulates
            # here, and is scrapeable next to the scale counters
            self.warm_entries_migrated += int(report.get("migrated_entries", 0))
            self.warm_pages_migrated += int(report.get("migrated_pages", 0))
            self.warm_pages_lost += int(report.get("lost_pages", 0))
        self._down_ok_at = now + self.cfg.down_cooldown_s
        self._down_ticks = 0
        self.flight.record("scale_down_report", **report)
        logger.info(
            "autoscaler: scaled down (-%s, drained=%s)",
            report["replica"],
            report["drained"],
        )
        return "scale_down"

    def _apply_degrade(self, on: bool) -> None:
        clamp = self.cfg.degrade_max_tokens if on else None
        for rep in list(self.router.replicas):
            sched = getattr(rep.engine, "scheduler", None)
            if sched is not None:
                sched.set_degrade(clamp)

    def _set_degrade(self, on: bool) -> str:
        self._apply_degrade(on)
        self.degrade_active = on
        with self._lock:
            if on:
                self.degrade_engaged += 1
            else:
                self.degrade_released += 1
        logger.info("autoscaler: degradation band %s", "ENGAGED" if on else "released")
        return "degrade_on" if on else "degrade_off"

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "SLOAutoscaler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"{self.name}-loop", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.tick()
            except Exception:
                # the controller must never die of a transient scrape error
                # (a replica mid-restart raising from a stats surface)
                logger.exception("autoscaler: tick failed")
            if self._sleep is not None:
                self._sleep(self.cfg.interval_s)
            else:
                self._stop.wait(self.cfg.interval_s)

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            # the control thread may be INSIDE a scale-down drain: the join
            # must outlast drain_deadline_s, or the registry would proceed to
            # stop engines while a zombie tick still mutates the fleet
            t.join(
                timeout=max(
                    5.0, 2 * self.cfg.interval_s, self.cfg.drain_deadline_s + 5.0
                )
            )
            if t.is_alive():  # pragma: no cover - pathological drain wedge
                logger.warning(
                    "autoscaler: control thread still draining at stop(); "
                    "proceeding (its replica was already detached from dispatch)"
                )
        self._thread = None
        if self.degrade_active:
            # never leave the fleet clamped after the controller goes away
            self._set_degrade(False)
        # close the replica-seconds integral up to NOW — accounting only, no
        # policy (a post-stop tick() could still actuate); idempotent because
        # _last_at advances with the accumulation, and locked against a
        # concurrent tick's own accumulation
        now = self._clock()
        with self._lock:
            if self._last_at is not None:
                self.replica_seconds += len(self.router.replicas) * max(
                    0.0, now - self._last_at
                )
                self._last_at = now

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        """One JSON-able snapshot for /healthz and the /metrics renderer."""
        with self._lock:
            return {
                "min_replicas": self.cfg.min_replicas,
                "max_replicas": self.cfg.max_replicas,
                "replicas": len(self.router.replicas),
                "slo_ttft_p95_s": self.cfg.slo_ttft_p95_s,
                "slo_itl_p95_s": self.cfg.up_itl_p95_s,
                "ticks": self.ticks,
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
                "scale_up_failures": self.scale_up_failures,
                # held-back scale-ups by reason: "at hardware limit"
                # (no_capacity — the mesh planner has no free slice) is
                # distinct from cooldown (flap damping) and bounds (the
                # configured max_replicas ceiling)
                "scale_up_skipped": dict(self.scale_up_skipped),
                "last_skip_reason": self.last_skip_reason,
                "at_hardware_limit": self._no_capacity,
                "warm_entries_migrated": self.warm_entries_migrated,
                "warm_pages_migrated": self.warm_pages_migrated,
                "warm_pages_lost": self.warm_pages_lost,
                "degrade_active": self.degrade_active,
                "degrade_engaged": self.degrade_engaged,
                "degrade_released": self.degrade_released,
                "replica_seconds": round(self.replica_seconds, 3),
                "last_decision": self.last_decision,
                "last_signals": dict(self.last_signals),
            }
