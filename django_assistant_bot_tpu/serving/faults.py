"""Deterministic, seeded fault injection for the serving plane.

Crash-only design (Candea & Fox) and chaos-engineering practice agree on one
point: recovery paths that are never exercised don't work.  This module is the
exerciser — a zero-overhead-when-off injection plane with *named sites* wired
into the engine loop and the HTTP provider client, driven either by exact
fire-on-Nth-call schedules (tests are exact, not flaky) or by a seeded
probability stream (same seed → same fire pattern, across processes).

Sites (the full set — unknown names are a config error, not a silent no-op):

================  ============================================================
``tick_raise``    the device decode/prefill dispatch raises (XLA error, TPU
                  preemption, OOM) — exercised at the top of the engine's
                  ``_issue_tick``; classified *engine-fatal* → crash-only
                  restart (see ``GenerationEngine._restart``)
``nan_logits``    a tick's sampled ids come back garbage (what a NaN'd logits
                  row yields after top-k/softmax) — the engine's host-side id
                  validation catches it and *quarantines* only the poisoned
                  slot, keeping its batch-mates alive
``detok_raise``   final detokenization raises — request-poison: fail that one
                  request, the engine keeps serving
``slow_tick``     latency injection: the engine loop sleeps ``delay_s`` before
                  a tick (heartbeat-age / wedged-loop detection evidence)
``timeout``       HTTP client: the request times out before a response
``conn_reset``    HTTP client: the connection drops mid-request
``http_5xx``      HTTP client: the server answers 503
``replica_dead``  router (serving/router.py): the replica the dispatcher is
                  about to pick dies abruptly — its engine loop exits and
                  fails in-flight work, exercising breaker trip + token-less
                  re-route (the fleet-level analogue of ``tick_raise``)
``replica_slow``  router: the dispatch hop to a replica stalls ``delay_s``
                  (slow replica admission / network hop evidence)
``task_raise``    task plane (tasks/queue.py): the task body raises before
                  doing any work — transient, exercises the retry/backoff/DLQ
                  ladder
``task_worker_lost``  task plane: the executing worker "dies" — consulted
                  before the body and after each delivered answer part
                  (bot/tasks.py), the row is left RUNNING with its lease, and
                  lease expiry + reclaim own the re-delivery (the exactly-once
                  ledger's chaos case)
``platform_http_429``  bot delivery: the platform answers flood control —
                  raised as ``RetryLater(delay_s)`` so the queue honors the
                  platform's pacing
``platform_http_5xx``  bot delivery: the platform answers a transient 5xx-
                  shaped connection error — exercises delivery re-raise +
                  queue retry
``net_drop``      fleet wire (serving/fleet.py PeerClient): the connection
                  drops AFTER the request was sent but before the response is
                  read — the server may have executed it, so this is the
                  idempotent-dispatch chaos case (timeout-retry must not
                  double-execute)
``net_delay``     fleet wire: the link stalls ``delay_s`` before the request
                  goes out (slow-link evidence for the connect/read timeout
                  split)
``net_corrupt``   fleet wire: one byte of a KV payload (octet-stream request
                  body, or octet-stream response body) is flipped in flight —
                  the CRC32C integrity check must reject it
``net_partition`` fleet wire: the peer is unreachable at connect time (both
                  sides alive, the link is down) — usually driven by a
                  ``start_after_s``/``duration_s`` window so the bench gets a
                  partition AND a heal
``net_blackhole`` fleet wire: the SYN black-holes (connect times out, nothing
                  answers) — distinct from ``net_partition`` only in detail
                  text; exercises the fast connect-timeout path
``disk_write_fail``  durability plane (storage/durable.py): a WAL append or
                  snapshot write fails up front (ENOSPC, EIO) — the mutation
                  must be rejected whole, never half-applied
``disk_torn_write``  durability plane: a WAL record write is cut mid-record
                  (power loss between write and fsync) — the file keeps a
                  torn tail that recovery must truncate, not trust
``snapshot_corrupt``  durability plane: one byte of a just-written snapshot
                  artifact flips (bit rot, partial page) — the manifest
                  digest walk must reject the snapshot and fall back to the
                  previous valid one
================  ============================================================

Each site's spec is either a bare float (fire probability) or a mapping with
any of: ``p`` (probability), ``fire_on`` (exact 1-based call indices),
``every`` (fire every Nth call), ``max_fires`` (stop after N fires),
``delay_s`` (sleep length for latency sites), ``start_after_s``/``duration_s``
(a clock window measured from the site's first consult — the partition/heal
schedule shape; fires for the whole window, composes with the other triggers,
and ignores ``max_fires`` so a window is never cut short by earlier fires),
and ``edges`` (restrict a site to specific consult keys — see below).
Schedules compose: a call fires if it matches ``fire_on`` OR ``every`` OR the
window OR the probability draw, until ``max_fires`` is exhausted.

Network sites are consulted **per edge**: ``should_fire(site, key=edge)``
where the edge is the caller's ``"{self}->{peer}"`` string.  Each (site, key)
pair keeps its own schedule state and its own RNG seeded
``f"{seed}:{site}:{key}"`` — the same seed reproduces the same per-edge
partition schedule across processes regardless of how edges interleave, which
is what makes a two-process chaos bench replayable.  A spec's ``edges`` list
scopes the site to those keys only (other edges never fire).

Gating: engines take an injector from ``ModelSpec.faults`` (explicit) or from
the ``DABT_FAULTS`` env var (JSON, with ``DABT_FAULT_SEED``); the HTTP client
uses the process-global env-gated injector.  With neither set, everything that
would consult an injector holds ``None`` and the hot path pays a single
``is None`` check — the inertness unit test in tests/test_faults.py asserts no
injector method is ever entered on a fault-free engine.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading
import time
from typing import Any, Callable, Dict, Mapping, Optional

ENGINE_SITES = ("tick_raise", "nan_logits", "detok_raise", "slow_tick")
HTTP_SITES = ("timeout", "conn_reset", "http_5xx")
# consulted by the multi-replica EngineRouter (serving/router.py), never by an
# engine: one spec can drive engine-, HTTP- and router-level chaos together
ROUTER_SITES = ("replica_dead", "replica_slow")
# consulted by the task plane (tasks/queue.py Worker.execute + bot/tasks.py
# delivery) via the lazy global-injector discipline — no engine involved
TASK_SITES = ("task_raise", "task_worker_lost", "platform_http_429", "platform_http_5xx")
# consulted by the fleet-wire PeerClient (serving/fleet.py) per edge — every
# consult carries a ``key`` ("router->peer" string) with its own seeded state
NET_SITES = ("net_drop", "net_delay", "net_corrupt", "net_partition", "net_blackhole")
# consulted by the retrieval durability plane (storage/durable.py) around WAL
# appends and snapshot writes, via the same lazy global-injector discipline as
# the task plane — the storage package never imports this module eagerly
STORAGE_SITES = ("disk_write_fail", "disk_torn_write", "snapshot_corrupt")
ALL_SITES = ENGINE_SITES + HTTP_SITES + ROUTER_SITES + TASK_SITES + NET_SITES + STORAGE_SITES

ENV_FAULTS = "DABT_FAULTS"
ENV_SEED = "DABT_FAULT_SEED"


class FaultInjected(RuntimeError):
    """An injected fault fired.  ``site`` names the injection point."""

    def __init__(self, site: str, detail: str = ""):
        super().__init__(f"injected fault: {site}" + (f" ({detail})" if detail else ""))
        self.site = site


@dataclasses.dataclass
class _Site:
    name: str
    probability: float = 0.0
    fire_on: frozenset = frozenset()
    every: int = 0
    max_fires: int = 0  # 0 = unlimited
    delay_s: float = 0.05
    # clock window measured from the site's first consult: fires while
    # start_after_s <= elapsed < start_after_s + duration_s (negative = off)
    start_after_s: float = -1.0
    duration_s: float = 0.0
    # consult keys (edges) this site is scoped to; empty = all
    edges: frozenset = frozenset()
    calls: int = 0
    fires: int = 0
    armed: int = 0  # fire unconditionally on the next N calls (tests)
    first_consult: Optional[float] = None
    last_fire_monotonic: Optional[float] = None


def _parse_site(name: str, spec: Any) -> _Site:
    if isinstance(spec, bool):
        raise ValueError(f"fault site {name!r}: spec must be a probability or mapping")
    if isinstance(spec, (int, float)):
        spec = {"p": float(spec)}
    if not isinstance(spec, Mapping):
        raise ValueError(f"fault site {name!r}: unparseable spec {spec!r}")
    unknown = set(spec) - {
        "p", "probability", "fire_on", "every", "max_fires", "delay_s",
        "start_after_s", "duration_s", "edges",
    }
    if unknown:
        raise ValueError(f"fault site {name!r}: unknown keys {sorted(unknown)}")
    p = float(spec.get("p", spec.get("probability", 0.0)))
    if not (0.0 <= p <= 1.0):
        raise ValueError(f"fault site {name!r}: probability {p} outside [0, 1]")
    fire_on = frozenset(int(n) for n in spec.get("fire_on", ()))
    if any(n < 1 for n in fire_on):
        raise ValueError(f"fault site {name!r}: fire_on indices are 1-based")
    start_after_s = float(spec.get("start_after_s", -1.0))
    duration_s = max(0.0, float(spec.get("duration_s", 0.0)))
    if start_after_s >= 0.0 and duration_s <= 0.0:
        raise ValueError(f"fault site {name!r}: start_after_s needs duration_s > 0")
    edges = spec.get("edges", ())
    if isinstance(edges, str) or not all(isinstance(e, str) for e in edges):
        raise ValueError(f"fault site {name!r}: edges must be a list of key strings")
    return _Site(
        name=name,
        probability=p,
        fire_on=fire_on,
        every=max(0, int(spec.get("every", 0))),
        max_fires=max(0, int(spec.get("max_fires", 0))),
        delay_s=max(0.0, float(spec.get("delay_s", 0.05))),
        start_after_s=start_after_s,
        duration_s=duration_s,
        edges=frozenset(edges),
    )


class FaultInjector:
    """Deterministic fire-pattern generator over named sites.

    Thread-safe: sites are consulted from the engine thread and asyncio
    threads concurrently.  Each site draws from its own ``random.Random``
    seeded by ``(seed, site name)`` so one site's call pattern can never
    perturb another's — and the same seed reproduces the same pattern
    regardless of how sites interleave.
    """

    def __init__(
        self,
        spec: Mapping[str, Any],
        *,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.seed = int(seed)
        self._clock = clock
        self._lock = threading.Lock()
        self._sites: Dict[str, _Site] = {}
        self._rngs: Dict[str, random.Random] = {}
        # per-(site, key) substates for edge-scoped consults: each edge clones
        # the base spec lazily and draws from its own str-seeded RNG, so one
        # edge's consult pattern can never perturb another's schedule
        self._subs: Dict[tuple, _Site] = {}
        self._sub_rngs: Dict[tuple, random.Random] = {}
        for name, site_spec in (spec or {}).items():
            if name not in ALL_SITES:
                raise ValueError(
                    f"unknown fault site {name!r}; expected one of {list(ALL_SITES)}"
                )
            self._sites[name] = _parse_site(name, site_spec)
            # str seeding is stable across processes (hashed via sha512, not
            # the per-process-salted hash()) — determinism is the contract
            self._rngs[name] = random.Random(f"{self.seed}:{name}")

    @classmethod
    def from_spec(
        cls, spec: Optional[Mapping[str, Any]], *, seed: int = 0
    ) -> Optional["FaultInjector"]:
        """None/empty spec → None: callers hold no injector at all, so the
        disabled path is a bare ``is None`` check."""
        if not spec:
            return None
        return cls(spec, seed=seed)

    @classmethod
    def from_env(cls, *, seed_offset: int = 0) -> Optional["FaultInjector"]:
        """Env-gated injector (DABT_FAULTS / DABT_FAULT_SEED).  ``seed_offset``
        shifts the seed per consumer — engine replicas use their index so
        probabilistic sites fire different (still deterministic) patterns per
        replica instead of N copies of one pattern failing in lockstep."""
        raw = os.environ.get(ENV_FAULTS, "").strip()
        if not raw:
            return None
        seed = int(os.environ.get(ENV_SEED, "0") or "0")
        return cls(json.loads(raw), seed=seed + int(seed_offset))

    # ------------------------------------------------------------------ sites
    def enabled(self, site: str) -> bool:
        return site in self._sites

    def arm(self, site: str, n: int = 1, *, key: str = "") -> None:
        """Fire unconditionally on the next ``n`` calls of ``site`` (tests:
        exact one-shot faults without counting call indices).  Arming a site
        absent from the spec registers it.  ``key`` arms one edge's substate
        only (other edges keep their own schedules)."""
        with self._lock:
            s = self._sites.get(site)
            if s is None:
                if site not in ALL_SITES:
                    raise ValueError(f"unknown fault site {site!r}")
                s = self._sites[site] = _Site(name=site)
                self._rngs[site] = random.Random(f"{self.seed}:{site}")
            if key:
                s = self._state(site, key)
            s.armed += int(n)

    def _state(self, site: str, key: str) -> _Site:
        """The (site, key) substate, lazily cloned from the base spec with
        fresh counters and its own cross-process-stable RNG.  Caller holds
        ``self._lock``; the base site must exist."""
        sub = self._subs.get((site, key))
        if sub is None:
            sub = dataclasses.replace(
                self._sites[site],
                calls=0, fires=0, armed=0,
                first_consult=None, last_fire_monotonic=None,
            )
            self._subs[(site, key)] = sub
            self._sub_rngs[(site, key)] = random.Random(f"{self.seed}:{site}:{key}")
        return sub

    def should_fire(self, site: str, key: str = "") -> bool:
        """Consult (and advance) a site's schedule.  Unconfigured sites never
        fire and keep no state.  ``key`` selects a per-edge substate (network
        sites) — each edge advances independently and deterministically."""
        with self._lock:
            base = self._sites.get(site)
            if base is None:
                return False
            if base.edges and key not in base.edges:
                return False
            s = self._state(site, key) if key else base
            rng = self._sub_rngs[(site, key)] if key else self._rngs[site]
            now = self._clock()
            if s.first_consult is None:
                s.first_consult = now
            s.calls += 1
            in_window = (
                s.start_after_s >= 0.0
                and s.start_after_s <= (now - s.first_consult) < s.start_after_s + s.duration_s
            )
            fire = False
            if in_window:
                # windows model link state (partitions), not discrete events —
                # they hold for the full duration regardless of max_fires
                fire = True
            elif s.max_fires and s.fires >= s.max_fires:
                return False
            elif s.armed > 0:
                s.armed -= 1
                fire = True
            elif s.calls in s.fire_on:
                fire = True
            elif s.every and s.calls % s.every == 0:
                fire = True
            elif s.probability and rng.random() < s.probability:
                fire = True
            if fire:
                s.fires += 1
                s.last_fire_monotonic = now
            return fire

    def maybe_raise(self, site: str, detail: str = "", *, key: str = "") -> None:
        if self.should_fire(site, key):
            raise FaultInjected(site, detail)

    def sleep_s(self, site: str, key: str = "") -> float:
        """Latency sites: the injected delay for this call (0.0 = no fire)."""
        if self.should_fire(site, key):
            with self._lock:
                s = self._subs[(site, key)] if key else self._sites[site]
                return s.delay_s
        return 0.0

    def raise_http_fault(self, url: str = "") -> None:
        """Consult the HTTP sites in a fixed order and raise the mapped client
        exception for the first that fires — called by the provider client
        before each attempt, so retry/failover paths are exercised without a
        misbehaving server."""
        if self.should_fire("timeout"):
            raise TimeoutError(f"injected fault: timeout ({url})")
        if self.should_fire("conn_reset"):
            raise ConnectionResetError(f"injected fault: conn_reset ({url})")
        if self.should_fire("http_5xx"):
            import aiohttp

            raise aiohttp.ClientResponseError(
                request_info=None,
                history=(),
                status=503,
                message=f"injected fault: http_5xx ({url})",
            )

    def last_fire_at(self, site: str, key: str = "") -> Optional[float]:
        """clock() stamp (default time.monotonic) of the site's most recent fire (bench: recovery
        time is measured from here to the next successful completion)."""
        with self._lock:
            s = self._subs.get((site, key)) if key else self._sites.get(site)
            return s.last_fire_monotonic if s is not None else None

    def stats(self) -> Dict[str, Dict[str, int]]:
        """Per-site call/fire counts; edge substates appear as ``site[key]``
        rows beside the base site (the chaos bench's injected-vs-rejected
        accounting reads the edge rows)."""
        with self._lock:
            out = {
                name: {"calls": s.calls, "fires": s.fires}
                for name, s in self._sites.items()
            }
            for (site, key), s in self._subs.items():
                out[f"{site}[{key}]"] = {"calls": s.calls, "fires": s.fires}
            return out


# Process-global injector for call sites without a per-engine spec (the HTTP
# provider client).  Loaded once from the environment; tests override via
# set_global_injector and MUST reset in teardown.
_global: Optional[FaultInjector] = None
_global_loaded = False
_global_lock = threading.Lock()


def global_injector() -> Optional[FaultInjector]:
    global _global, _global_loaded
    if _global_loaded:
        return _global
    with _global_lock:
        if not _global_loaded:
            _global = FaultInjector.from_env()
            _global_loaded = True
    return _global


def set_global_injector(inj: Optional[FaultInjector]) -> None:
    global _global, _global_loaded
    with _global_lock:
        _global = inj
        _global_loaded = True


def reset_global_injector() -> None:
    """Forget the cached global injector (re-reads the env on next use)."""
    global _global, _global_loaded
    with _global_lock:
        _global = None
        _global_loaded = False
