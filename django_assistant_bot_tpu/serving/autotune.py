"""Byte-ledger decode geometry autotuner (docs/QUANT.md "Autotuning").

Decode is HBM-bandwidth-bound, so its steady-state rate is predictable from
bytes alone: every step reads the (quantized) weights once for the whole
batch plus each live slot's KV window, and every tick pays a host dispatch
overhead that ``decode_steps`` amortizes.  This module sweeps
``kv_page_size x max_slots x decode_steps`` through that ledger — the same
byte model ``bench.decode_byte_ledger`` reports against measurements — and
emits the config that maximizes modeled tok/s under an HBM byte budget.

Pure arithmetic over plain ints/floats: no jax import, so the standalone
``tools/autotune.py`` wrapper runs it anywhere and ``cli serve --autotune``
runs it before any weight load.  The model is a RANKING device, not a
prophecy — absolute tok/s depends on the chip's achieved bandwidth, which is
why the recommendation records the assumptions (``hbm_gbps``,
``host_overhead_us``) alongside the ranking, and why the bench's interleaved
A/B arms stay the ground truth for any claim.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, List, Mapping, Optional, Sequence

# v5e-ish defaults; override from measurements (tick_stats issue_ms, the
# bench's decode_hbm_stream_probe_gbps) when you have them
DEFAULT_HBM_GBPS = 819.0
DEFAULT_HOST_OVERHEAD_US = 150.0
DEFAULT_HBM_BUDGET_GB = 16.0

WEIGHT_SCALE_BYTES = 4  # f32 quantization scales


@dataclasses.dataclass(frozen=True)
class Geometry:
    """The decoder shape the ledger needs — constructible from a
    DecoderConfig (``from_decoder_config``) or raw ints (the tools/ CLI)."""

    num_layers: int
    hidden_size: int
    intermediate_size: int
    num_heads: int
    num_kv_heads: int
    head_dim: int
    vocab_size: int
    num_experts: int = 0
    tie_embeddings: bool = False
    dtype_bytes: int = 2  # bf16

    @classmethod
    def from_decoder_config(cls, cfg: Any) -> "Geometry":
        import jax.numpy as jnp

        return cls(
            num_layers=cfg.num_layers,
            hidden_size=cfg.hidden_size,
            intermediate_size=cfg.intermediate_size,
            num_heads=cfg.num_heads,
            num_kv_heads=cfg.num_kv_heads,
            head_dim=cfg.head_dim,
            vocab_size=cfg.vocab_size,
            num_experts=getattr(cfg, "num_experts", 0) or 0,
            tie_embeddings=bool(cfg.tie_embeddings),
            dtype_bytes=jnp.dtype(cfg.dtype).itemsize,
        )

    def projection_weights(self) -> int:
        """Layer-projection weight count (the quantizable set)."""
        E, F = self.hidden_size, self.intermediate_size
        H, KH, D = self.num_heads, self.num_kv_heads, self.head_dim
        attn = E * H * D + 2 * E * KH * D + H * D * E
        mlp = 3 * E * F
        if self.num_experts:
            mlp *= self.num_experts
        return self.num_layers * (attn + mlp)

    def head_weights(self) -> int:
        # tied models read the embedding table as the head
        return self.hidden_size * self.vocab_size

    def weight_read_bytes(self, weight_bits: int, group_size: int = 64) -> int:
        """Bytes one decode step streams for weights: packed projections +
        their scales (int8: one f32/channel; int4: one f32 per group x
        channel) + the bf16 head/embedding read."""
        proj = self.projection_weights()
        if weight_bits == 4:
            b = proj // 2 + (proj // max(2, group_size)) * WEIGHT_SCALE_BYTES
        elif weight_bits == 8:
            # per-output-channel scales: ~proj / contraction_dim entries —
            # approximate with E as the typical contraction width
            b = proj + (proj // max(1, self.hidden_size)) * WEIGHT_SCALE_BYTES
        else:
            b = proj * self.dtype_bytes
        return b + self.head_weights() * self.dtype_bytes

    def resident_weight_bytes(self, weight_bits: int, group_size: int = 64) -> int:
        """HBM bytes the weights OCCUPY (the feasibility side): the per-step
        read plus, for untied models, the second embedding table — decode
        streams only the head, but tok_embed sits in HBM regardless (at
        8B/128k vocab that second bf16 table is ~1 GB the budget must
        charge)."""
        b = self.weight_read_bytes(weight_bits, group_size)
        if not self.tie_embeddings:
            b += self.head_weights() * self.dtype_bytes
        return b

    def kv_row_bytes_per_token(self, kv_itemsize: int) -> int:
        return self.num_layers * self.num_kv_heads * self.head_dim * 2 * kv_itemsize


@dataclasses.dataclass
class Candidate:
    kv_page_size: int
    max_slots: int
    decode_steps: int
    est_tokens_per_s: float
    est_step_ms: float
    step_read_gb: float
    kv_alloc_gb: float
    hbm_total_gb: float

    def as_dict(self) -> dict:
        return {k: round(v, 4) if isinstance(v, float) else v
                for k, v in dataclasses.asdict(self).items()}


def _page_candidates(max_seq_len: int, pages: Sequence[int]) -> List[int]:
    return [p for p in pages if max_seq_len % p == 0 and max_seq_len // p >= 2]


def sweep(
    geom: Geometry,
    *,
    max_seq_len: int,
    fill_len: Optional[int] = None,
    weight_bits: int = 16,
    group_size: int = 64,
    kv_itemsize: Optional[int] = None,
    hbm_budget_gb: float = DEFAULT_HBM_BUDGET_GB,
    hbm_gbps: float = DEFAULT_HBM_GBPS,
    host_overhead_us: float = DEFAULT_HOST_OVERHEAD_US,
    page_sizes: Sequence[int] = (32, 64, 128, 256, 512),
    slots: Sequence[int] = (4, 8, 16, 32, 64, 128),
    decode_steps: Sequence[int] = (1, 2, 4, 8, 16),
) -> List[Candidate]:
    """Rank every feasible (page, slots, steps) triple by modeled tok/s.

    Model, per decode step: ``bytes = weight_read + slots * kv_row_bytes *
    covered(fill, page)`` where ``covered`` rounds the fill up to page
    granularity (the paged read is inherently page-chunked); ``device_step_s
    = bytes / hbm_gbps``; one tick of N steps costs ``N * device_step_s +
    host_overhead`` so ``tok/s = slots * N / tick_s``.  Feasibility: weights
    + byte-parity page pool (slots x max_seq_len) must fit ``hbm_budget_gb``.
    """
    kv_itemsize = kv_itemsize or geom.dtype_bytes
    fill = min(int(fill_len) if fill_len else max_seq_len, max_seq_len)
    w_read = geom.weight_read_bytes(weight_bits, group_size)
    # resident weight bytes (pool feasibility): the read set plus the untied
    # embedding table that decode never streams but HBM must hold
    w_resident = geom.resident_weight_bytes(weight_bits, group_size)
    row_b = geom.kv_row_bytes_per_token(kv_itemsize)
    overhead_s = host_overhead_us / 1e6
    bw = hbm_gbps * 1e9
    out: List[Candidate] = []
    for page in _page_candidates(max_seq_len, page_sizes):
        covered = min(max_seq_len, ((max(1, fill) - 1) // page + 1) * page)
        for n_slots in slots:
            kv_alloc = n_slots * max_seq_len * row_b
            total = w_resident + kv_alloc
            if total > hbm_budget_gb * 1e9:
                continue
            step_bytes = w_read + n_slots * row_b * covered
            dev_step_s = step_bytes / bw
            for n_steps in decode_steps:
                tick_s = n_steps * dev_step_s + overhead_s
                tok_s = n_slots * n_steps / tick_s
                out.append(
                    Candidate(
                        kv_page_size=page,
                        max_slots=n_slots,
                        decode_steps=n_steps,
                        est_tokens_per_s=tok_s,
                        est_step_ms=tick_s / n_steps * 1e3,
                        step_read_gb=step_bytes / 1e9,
                        kv_alloc_gb=kv_alloc / 1e9,
                        hbm_total_gb=total / 1e9,
                    )
                )
    out.sort(key=lambda c: -c.est_tokens_per_s)
    return out


def recommend(
    geom: Geometry,
    *,
    max_seq_len: int,
    **kwargs: Any,
) -> dict:
    """The sweep's winner as a ModelSpec-shaped knob dict plus the modeling
    assumptions and the top alternatives — what ``serve --autotune`` prints."""
    cands = sweep(geom, max_seq_len=max_seq_len, **kwargs)
    if not cands:
        return {
            "error": "no feasible geometry under the HBM budget",
            "assumptions": _assumptions(kwargs),
        }
    best = cands[0]
    return {
        "recommended": {
            "kv_page_size": best.kv_page_size,
            "max_slots": best.max_slots,
            "decode_steps": best.decode_steps,
        },
        "est_tokens_per_s": round(best.est_tokens_per_s, 1),
        "est_step_ms": round(best.est_step_ms, 4),
        "hbm_total_gb": round(best.hbm_total_gb, 3),
        "assumptions": _assumptions(kwargs),
        "top": [c.as_dict() for c in cands[:8]],
    }


def _assumptions(kwargs: Mapping[str, Any]) -> dict:
    return {
        "hbm_gbps": kwargs.get("hbm_gbps", DEFAULT_HBM_GBPS),
        "host_overhead_us": kwargs.get(
            "host_overhead_us", DEFAULT_HOST_OVERHEAD_US
        ),
        "hbm_budget_gb": kwargs.get("hbm_budget_gb", DEFAULT_HBM_BUDGET_GB),
        "weight_bits": kwargs.get("weight_bits", 16),
        "note": "byte-ledger model — a ranking device; verify any claim "
        "with the bench's interleaved A/B arms",
    }


def recommend_for_spec(
    spec: Any,
    cfg: Any,
    *,
    n_host_devices: Optional[int] = None,
    hbm_gb_per_device: Optional[float] = None,
    **overrides: Any,
) -> dict:
    """Autotune one decoder ModelSpec against its (already-parsed) model
    config — the ``cli serve --autotune`` entry point.

    Slice awareness (docs/MULTICHIP.md): on a mesh-sliced fleet
    (``spec.replica_devices > 0``) the budget that matters is what ONE
    replica's slice can hold — ``replica_devices`` chips — not the whole
    host; a whole-host budget would recommend a geometry a sliced replica
    cannot place.  ``hbm_gb_per_device`` is the per-chip HBM (default
    :data:`DEFAULT_HBM_BUDGET_GB`); the effective budget is per-chip x
    slice devices.  Unsliced specs keep the historical semantics (the
    budget names one replica's whole mesh — all of ``n_host_devices`` when
    given, else the single-chip default).  An explicit ``hbm_budget_gb``
    override wins over both.
    """
    import jax.numpy as jnp

    geom = Geometry.from_decoder_config(cfg)
    weight_bits = {"int8": 8, "int4": 4}.get(spec.quantize or "", 16)
    kv_itemsize = (
        1
        if (spec.kv_cache_dtype or "").startswith("fp8")
        else jnp.dtype(cfg.dtype).itemsize
    )
    replica_devices = int(getattr(spec, "replica_devices", 0) or 0)
    slice_devices = replica_devices or int(n_host_devices or 1)
    kwargs = {
        "fill_len": None,
        "weight_bits": weight_bits,
        "group_size": getattr(spec, "quant_group_size", 64),
        "kv_itemsize": kv_itemsize,
        **overrides,
    }
    if "hbm_budget_gb" not in kwargs and (
        replica_devices or hbm_gb_per_device is not None or n_host_devices
    ):
        per_chip = (
            hbm_gb_per_device
            if hbm_gb_per_device is not None
            else DEFAULT_HBM_BUDGET_GB
        )
        kwargs["hbm_budget_gb"] = per_chip * slice_devices
    if getattr(spec, "speculative", 0):
        # spec x fused: decode_steps now scans N verify passes per dispatch
        # (docs/SPECULATIVE.md "Spec x fused"), so the sweep covers it — but
        # the engine bounds decode_steps * (K+1) against max_seq_len // 4, so
        # drop depths a speculative engine would refuse to boot at
        max_sl = int(min(spec.max_seq_len or cfg.max_seq_len, cfg.max_seq_len))
        k1 = int(spec.speculative) + 1
        feasible = tuple(
            n for n in (1, 2, 4, 8, 16) if n * k1 <= max_sl // 4
        ) or (1,)
        kwargs.setdefault("decode_steps", feasible)
    max_seq_len = int(
        min(spec.max_seq_len or cfg.max_seq_len, cfg.max_seq_len)
    )
    out = recommend(geom, max_seq_len=max_seq_len, **kwargs)
    out["model"] = spec.name
    out["max_seq_len"] = max_seq_len
    # what the budget was sized FOR: one replica's devices (its slice on a
    # sliced fleet, the whole mesh otherwise)
    out["slice_devices"] = slice_devices
    out["sliced"] = bool(replica_devices)
    return out


def measure_report(
    report: dict,
    engine_factory: Any,
    *,
    top_k: int = 3,
    iters: int = 16,
    fill_len: Optional[int] = None,
) -> dict:
    """Measured-cost re-ranking (``serve --autotune --measure``).

    Compiles and micro-probes the ``top_k`` ledger-ranked candidates from a
    :func:`recommend`/:func:`recommend_for_spec` report on the live device.
    ``engine_factory(candidate_dict)`` must return a constructed engine
    exposing ``probe_decode(iters=, fill_len=)`` -> seconds/step and
    ``stop()`` — the GenerationEngine probe runs idle-locked burst ticks
    with device-chained state, so the measurement IS the compiled program's
    per-step device cost at that geometry, not the ledger's guess.

    The report keeps BOTH rankings: ``recommended`` becomes the measured
    winner, the ledger's pick moves to ``ledger_recommended``, and
    ``measured_agrees_with_ledger`` makes disagreement a visible artifact
    (the ledger is a ranking device; the probe is ground truth for step
    cost — the bench's interleaved arms remain ground truth for end-to-end
    claims).  A candidate whose compile/probe fails is recorded with
    ``probe_error`` and excluded from the re-rank instead of failing the
    whole measurement.
    """
    top = list(report.get("top") or [])
    if not top:
        report["measure_error"] = "no feasible candidates to probe"
        return report
    probed: List[dict] = []
    for rank, cand in enumerate(top[: max(1, int(top_k))]):
        row = dict(cand)
        row["ledger_rank"] = rank
        eng = None
        try:
            eng = engine_factory(cand)
            step_s = float(eng.probe_decode(iters=iters, fill_len=fill_len))
            row["measured_step_ms"] = round(step_s * 1e3, 4)
            # every probed step advances all max_slots rows one token
            row["measured_tokens_per_s"] = round(cand["max_slots"] / step_s, 1)
        except Exception as e:  # record, don't abort the sweep
            row["probe_error"] = f"{type(e).__name__}: {e}"
        finally:
            if eng is not None:
                try:
                    eng.stop(drain_timeout_s=1.0)
                except Exception:  # pragma: no cover - teardown belt
                    pass
        probed.append(row)
    ok = [r for r in probed if "measured_tokens_per_s" in r]
    report["measured"] = sorted(
        probed, key=lambda r: -r.get("measured_tokens_per_s", -1.0)
    )
    if not ok:
        report["measure_error"] = "every candidate probe failed"
        return report
    best = max(ok, key=lambda r: r["measured_tokens_per_s"])
    report["ledger_recommended"] = dict(report.get("recommended") or {})
    report["recommended"] = {
        "kv_page_size": best["kv_page_size"],
        "max_slots": best["max_slots"],
        "decode_steps": best["decode_steps"],
    }
    report["measured_agrees_with_ledger"] = bool(best["ledger_rank"] == 0)
    return report


def main(argv: Optional[Sequence[str]] = None) -> int:
    """Standalone CLI body (``python tools/autotune.py`` delegates here)."""
    import argparse

    ap = argparse.ArgumentParser(
        description="byte-ledger decode geometry autotuner (docs/QUANT.md)"
    )
    ap.add_argument("--layers", type=int, required=True)
    ap.add_argument("--hidden", type=int, required=True)
    ap.add_argument("--intermediate", type=int, required=True)
    ap.add_argument("--heads", type=int, required=True)
    ap.add_argument("--kv-heads", type=int, required=True)
    ap.add_argument("--head-dim", type=int, required=True)
    ap.add_argument("--vocab", type=int, required=True)
    ap.add_argument("--max-seq-len", type=int, required=True)
    ap.add_argument("--experts", type=int, default=0)
    ap.add_argument(
        "--tied",
        action="store_true",
        help="embeddings tied to the head (one table resident, not two)",
    )
    ap.add_argument("--fill-len", type=int, default=None)
    ap.add_argument("--weight-bits", type=int, default=16, choices=(4, 8, 16))
    ap.add_argument("--group-size", type=int, default=64)
    ap.add_argument("--kv-itemsize", type=int, default=2)
    ap.add_argument("--hbm-budget-gb", type=float, default=DEFAULT_HBM_BUDGET_GB)
    ap.add_argument("--hbm-gbps", type=float, default=DEFAULT_HBM_GBPS)
    ap.add_argument(
        "--host-overhead-us", type=float, default=DEFAULT_HOST_OVERHEAD_US
    )
    args = ap.parse_args(argv)
    geom = Geometry(
        num_layers=args.layers,
        hidden_size=args.hidden,
        intermediate_size=args.intermediate,
        num_heads=args.heads,
        num_kv_heads=args.kv_heads,
        head_dim=args.head_dim,
        vocab_size=args.vocab,
        num_experts=args.experts,
        tie_embeddings=args.tied,
    )
    out = recommend(
        geom,
        max_seq_len=args.max_seq_len,
        fill_len=args.fill_len,
        weight_bits=args.weight_bits,
        group_size=args.group_size,
        kv_itemsize=args.kv_itemsize,
        hbm_budget_gb=args.hbm_budget_gb,
        hbm_gbps=args.hbm_gbps,
        host_overhead_us=args.host_overhead_us,
    )
    print(json.dumps(out, indent=2))
    return 0
