"""Continuous-batching generation engine + coalescing embedding engine.

This is the serving-side fix for the two deficiencies SURVEY.md §3.3 flags in the
reference's gpu_service: the unbatched per-text embedding loop
(assistant/ai/embedders/transformers.py:15-29) and single-stream ``generate`` with no
KV-cache reuse across requests (assistant/ai/providers/transformers.py:35-94).

Design (TPU-first):

- **Slot-based continuous batching.**  A fixed-size KV cache (``max_slots`` rows)
  lives in HBM.  New requests are prefilled on their own small batch (bucketed
  sequence lengths — a handful of compiled shapes, no dynamic shapes ever), then
  their K/V rows are inserted into free slots; one jit'd ``decode_tick`` advances
  *all* live slots a token per call.  Requests join and leave the batch without
  recompilation or disturbing other streams.
- **Sampling on device.**  temperature/top-p ride as [slots] arrays inside the tick;
  only sampled token ids (a few ints) cross back to host per step.
- **Cache donation.**  The decode tick donates the cache buffers, so XLA updates the
  multi-GB cache in place instead of copying.
- **Dedicated engine thread.**  Device steps are blocking; the engine runs them on
  its own thread and talks to asyncio via thread-safe futures, so the HTTP event
  loop never stalls (the reference instead forked gunicorn workers with a full model
  replica each — gpu_service/gunicorn_conf.py:9).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import logging
import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models import DecoderConfig, EncoderConfig, encoder, llama
from ..ops.sampling import sample_logits
from .obs import EngineObs, new_trace_id
from .scheduler import DeadlineExceeded, RequestScheduler, SchedulerRejected
from .tokenizer import Tokenizer

logger = logging.getLogger(__name__)

PREFILL_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048, 4096, 8192)


class RequestPoisoned(RuntimeError):
    """A failure attributable to ONE request (garbage sampled ids from a NaN'd
    logits row, a detokenization crash): that request's future fails with this
    and its slot is quarantined — batch-mates keep decoding."""

    def __init__(self, detail: str, slot: Optional[int] = None):
        super().__init__(detail)
        self.slot = slot


class EngineUnavailable(RuntimeError):
    """The engine's restart circuit is open (too many crash-only restarts in
    the window): ``submit()`` fast-fails with this instead of queueing work
    the engine cannot serve.  The HTTP layer maps it to 503 + ``Retry-After``
    (``retry_after_s`` is the remaining cooldown)."""

    def __init__(self, detail: str, retry_after_s: float):
        super().__init__(f"{detail} (retry after {retry_after_s:.1f}s)")
        self.retry_after_s = float(retry_after_s)


def _resident_bytes(tree) -> int:
    """Device-RESIDENT bytes of a pytree: one charge per addressable shard,
    so an array replicated across a mesh axis is charged per copy and a
    sharded array is charged exactly once in total.  Host (numpy) leaves
    charge their plain nbytes.  Init-time accounting only (the per-slice HBM
    ledger, docs/MULTICHIP.md) — reads array METADATA, never device memory."""
    total = 0
    for leaf in jax.tree.leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            total += sum(int(s.data.nbytes) for s in shards)
        else:
            total += int(getattr(leaf, "nbytes", 0))
    return total


def _replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P

    return NamedSharding(mesh, P())


def _safe_resolve(fut: Future, *, result=None, exc: Optional[BaseException] = None):
    """set_result/set_exception tolerant of a client cancelling concurrently."""
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except InvalidStateError:  # future was cancelled mid-flight
        pass


def pick_bucket(n: int, buckets: Sequence[int], cap: int) -> int:
    for b in buckets:
        if n <= b and b <= cap:
            return b
    return cap


@dataclasses.dataclass
class GenerationResult:
    token_ids: List[int]
    text: str
    prompt_tokens: int
    completion_tokens: int
    length_limited: bool
    ttft_s: float = 0.0
    latency_s: float = 0.0

    def usage_dict(self, model: str) -> dict:
        """The wire-format usage object (HTTP responses, provider AIResponse
        usage, SSE terminal events) — one construction for every consumer."""
        return {
            "model": model,
            "prompt_tokens": self.prompt_tokens,
            "completion_tokens": self.completion_tokens,
            "total_tokens": self.prompt_tokens + self.completion_tokens,
            "ttft_s": self.ttft_s,
            "latency_s": self.latency_s,
        }


@dataclasses.dataclass
class _Request:
    prompt_ids: List[int]
    max_tokens: int
    temperature: float
    top_p: float
    future: Future
    submitted_at: float
    json: bool = False  # grammar-constrained JSON decoding (ops/json_fsm.py)
    # leading prompt tokens that form a cacheable shared prefix (system prompt
    # + packed RAG context); 0 = no prefix-cache participation
    prefix_len: int = 0
    first_token_at: Optional[float] = None
    # scheduling metadata (serving/scheduler.py): class tag, fair-share tenant,
    # absolute monotonic deadline, and whether try_admit already reserved depth
    priority: str = "interactive"
    tenant: str = "default"
    deadline_at: Optional[float] = None
    admitted: bool = False
    # slot-residency start (prefill begins): the service-time sample the
    # scheduler's estimated-wait model is fed on finish
    started_at: Optional[float] = None
    # crash-only restarts this request survived (re-submitted with no tokens
    # emitted); bounded by the engine's max_request_restarts so one poisoned
    # prompt that deterministically kills the device cannot retry forever
    restarts: int = 0
    # per-request token event sink (serving/streaming.py TokenStream): fed a
    # deque-append per sampled id from _process_tick — already host-resident
    # data, so streaming adds zero device syncs.  None = request/response.
    stream: Any = None
    # paged KV plane: worst-case page reservation (ceil((prompt + max_tokens)
    # / page_size)) — the scheduler's KV-pressure admission charge
    kv_pages: int = 0
    # observability (serving/obs.py): the request/trace correlation id —
    # client X-Request-Id or generated at submit; stable across router
    # re-route hops and crash-restart re-submissions
    trace_id: str = ""
    # host-tier KV restore: admission found this request's prefix in the
    # host tier and uploaded it into fresh pages ahead of the suffix prefill
    # (the restores-in-flight gauge decrements when the slot activates)
    restored_from_host: bool = False


# slot-cache precision knob -> concrete dtype (None = the model's cfg.dtype);
# "bf16" is explicit bfloat16 even on f32 dev models, fp8 halves KV bytes
KV_CACHE_DTYPES = {
    None: None,
    "bf16": jnp.bfloat16,
    "fp8": jnp.float8_e4m3fn,
    "fp8_e5m2": jnp.float8_e5m2,
}


@dataclasses.dataclass
class _Prefix:
    """One cached prompt prefix: post-RoPE K/V at absolute positions [0, pb).

    ``length`` is the true prefix token count; ``pb`` the padded bucket the
    device tensors carry ([L, KH, pb, D] each) — the garbage tail [length, pb)
    is overwritten or masked by the consuming suffix prefill."""

    pk: Any
    pv: Any
    length: int
    pb: int


@dataclasses.dataclass
class _HostHit:
    """A prefix found in the HOST tier (the HBM registry missed).  Admission
    allocates fresh pages, uploads the spilled K/V into them ahead of the
    slot's suffix prefill (restore-then-suffix-prefill — bit-identical to a
    cold full prefill, since the bytes ARE the prefill's bytes), and
    re-registers the restored pages so later requests share them in HBM.
    Carries ``.length`` so the admission/suffix machinery treats it exactly
    like a device registry hit."""

    entry: Any  # kv_pool.HostPrefixEntry

    @property
    def length(self) -> int:
        return self.entry.length


@dataclasses.dataclass
class _Slot:
    request: _Request
    generated: List[int] = dataclasses.field(default_factory=list)
    # host arrival time of the previous token (inter-token-latency samples)
    last_token_at: Optional[float] = None
    # decode steps this slot sat through (fused ticks advance it by the tick's
    # step count even when EOS lands mid-tick) — the per-token denominator the
    # scheduler's service-time EMA needs so N-step ticks don't inflate the
    # predicted queue wait (docs/SCHEDULING.md)
    resident_steps: int = 0
    # prefill chunk dispatches this request consumed before activation —
    # charged to the scheduler's per-token service model alongside
    # resident_steps so piggybacked (continuous-batching) prefill work
    # doesn't vanish from the predicted queue wait / Retry-After math
    prefill_chunks: int = 0


@dataclasses.dataclass
class _TickRef:
    """One issued-but-not-yet-processed device result.

    ``slots`` records (slot, epoch) for every slot that was live at issue time;
    processing skips entries whose slot epoch has moved on (request finished by an
    earlier tick — its later speculative tokens are garbage and are dropped).

    ``first=True`` marks an activation: ``nxt`` is the [Bp] first sampled tokens
    of a freshly-prefilled admission wave (kept on device so admission never
    blocks on a host round trip); entry ``offset + j`` belongs to ``slots[j]``
    (rows below ``offset`` are batch-bucket padding).  FIFO order in the
    inflight deque guarantees they are appended before any burst tokens of the
    same slots.
    """

    nxt: Any  # device array: [burst, max_slots] sampled ids, or [Bp] when first
    slots: List[tuple]
    first: bool = False
    offset: int = 0
    # speculative tick: [max_slots] valid-token counts — entry k of nxt[:, b]
    # is real only for k < n_new[b] (the rest are rejected-draft garbage)
    n_new: Any = None
    # (width, depth) rung the speculative tick drafted at (the controller may
    # issue a narrower/shallower rung than the config maximum — acceptance
    # accounting needs the per-tick value, not the engine knob)
    spec_rung: Any = None


@dataclasses.dataclass
class _ChunkedPrefill:
    """An in-flight chunked prefill: one chunk advances per engine-loop iteration,
    interleaved with decode ticks (prefill/decode disaggregation)."""

    request: _Request
    slot: int
    ids: np.ndarray  # [n_chunks, chunk_size] — every chunk is full of real tokens
    starts: List[int]  # absolute start position of each chunk
    n: int  # true prompt length
    step: int = 0  # chunks completed


class GenerationEngine:
    """Continuous-batching decode engine over one decoder model."""

    def __init__(
        self,
        cfg: DecoderConfig,
        params,
        tokenizer: Tokenizer,
        *,
        max_slots: int = 8,
        max_seq_len: Optional[int] = None,
        top_k: int = 50,
        prefill_buckets: Sequence[int] = PREFILL_BUCKETS,
        idle_poll_s: float = 0.002,
        chunk_size: int = 512,
        lookahead: int = 3,
        burst: int = 8,
        decode_steps: Optional[int] = None,
        prefix_cache_size: int = 8,
        prefix_min_tokens: int = 32,
        prefix_cache_max_bytes: int = 1 << 30,
        kv_cache_dtype: Optional[str] = None,
        speculative: int = 0,
        spec_width: int = 4,
        spec_probe_every: int = 64,
        spec_explore_every: int = 32,
        decode_kv_chunk: Optional[int] = 0,
        prefill_piggyback: bool = True,
        attn_fp8: bool = False,
        kv_layout: str = "paged",
        kv_page_size: int = 0,
        kv_pages: int = 0,
        kv_host_bytes: int = 0,
        kv_spill_dir: Optional[str] = None,
        kv_host_writethrough: bool = True,
        scheduler: Optional[RequestScheduler] = None,
        faults=None,
        max_restarts: int = 5,
        restart_window_s: float = 60.0,
        restart_backoff_s: float = 0.05,
        restart_backoff_max_s: float = 2.0,
        degraded_cooldown_s: float = 30.0,
        heartbeat_degraded_s: float = 30.0,
        max_request_restarts: int = 2,
        name: str = "engine",
        obs: bool = True,
        obs_dump_dir: Optional[str] = None,
        mesh=None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        # Injectable time (dabtlint DABT105): every timestamp and backoff in
        # the engine flows through these two callables, so fake-clock tests
        # can drive deadlines/backoff/heartbeats deterministically.  Defaults
        # are the real thing — production behavior is byte-identical.
        self._clock = clock
        self._sleep = sleep
        # Observability plane (serving/obs.py, docs/OBSERVABILITY.md): span
        # traces, metric histograms and the crash flight recorder.  On by
        # default — recording is pure host bookkeeping over values the tick
        # path already holds (enforced by dabtlint's DABT104 registry), and
        # the bench's obs_* A/B keeps the overhead claim honest.  obs=False
        # is the A/B off-arm: no recorder object exists at all, the hot path
        # pays one `is None` check (the faults-plane discipline).
        self.name = name
        if obs:
            self.obs = EngineObs(name=name, clock=clock, dump_dir=obs_dump_dir)
        else:
            self.obs = None
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.max_slots = max_slots
        self.max_seq_len = int(min(max_seq_len or cfg.max_seq_len, cfg.max_seq_len))
        self.top_k = top_k
        self.prefill_buckets = tuple(b for b in prefill_buckets if b <= self.max_seq_len) or (
            self.max_seq_len,
        )
        self.idle_poll_s = idle_poll_s
        # Prompts longer than one chunk prefill incrementally: one chunk per engine
        # loop iteration, a decode tick for the live slots in between.  Decode
        # head-of-line blocking is bounded by a chunk, not by the longest prompt.
        self.chunk_size = int(min(chunk_size, self.max_seq_len))
        # Decode lookahead pipeline: ticks are issued with the *device* token array
        # chained tick-to-tick (no host value needed), results stream back via
        # copy_to_host_async, and the host processes them `lookahead` ticks behind.
        # This hides the host<->device round trip — measured 120 ms/tick synced vs
        # 7 ms/tick at depth 16 under a remote-device tunnel; even on local PCIe it
        # removes a blocking sync per token.  Cost: up to `lookahead` speculative
        # ticks per finished request (their tokens are dropped via slot epochs).
        self.lookahead = max(0, int(lookahead))
        # Fused multi-token decode tick: one jit call advances every live slot
        # `decode_steps` tokens via a lax.scan over chained decode steps
        # (gather -> attention -> MLP -> sample, donated cache chain), so host
        # bookkeeping, sampling-array uploads, and per-dispatch overhead (the
        # decode bottleneck once ticks are pipelined — each dispatch is an RPC
        # under a remote-device tunnel and a host round trip locally) amortise
        # over N tokens.  `decode_steps` is the canonical knob (docs/QUANT.md
        # roofline notes); `burst` is its historical alias and keeps working.
        # Costs: finished slots decode garbage for the rest of their tick
        # (dropped via slot epochs), admission waits for the tick in flight
        # (bounded by N * per-step time, same order as a prefill chunk), and
        # deadline/cancel reaping happens at tick granularity — a reaped slot
        # can burn up to N-1 extra garbage steps before it freezes.
        # JSON-constrained (json_fsm) slots disable fusion: while any json
        # slot is live the engine issues SINGLE-step ticks (the json tick
        # program is built with steps=1), so FSM semantics never depend on a
        # multi-step scan — `decode_steps_effective` in tick_stats shows
        # which path is active.
        if decode_steps is not None and int(decode_steps) < 1:
            raise ValueError(f"decode_steps must be >= 1 (got {decode_steps})")
        # Spec x fused composition (docs/SPECULATIVE.md): a tree-verify step
        # IS a multi-token tick, so `decode_steps` now scans N verify steps
        # into one speculative dispatch instead of being rejected.  A
        # speculative engine still defaults to ONE verify step per tick
        # unless decode_steps is set explicitly — the historical `burst`
        # default (8) describes plain-decode dispatch amortization and would
        # silently 8x the per-tick token budget of every existing spec
        # deployment.
        if decode_steps is not None:
            self.burst = max(1, int(decode_steps))
        else:
            self.burst = 1 if speculative else max(1, int(burst))
        # Tree-verified prompt-lookup speculative decoding
        # (ops/speculative.py): per tick, the on-device n-gram drafter emits
        # the top-`spec_width` distinct continuations of depth `speculative`
        # as a static token TREE, one fused forward verifies every node
        # through a precomputed ancestor mask, and acceptance takes the
        # longest root-to-leaf path matching the model's argmax — greedy rows
        # advance up to K+1 tokens per tick at identical output.  The
        # reference's answer-from-context workload is the high-acceptance
        # regime.  An acceptance-EMA controller shrinks the tree (then
        # disables speculation) below the measured verify/decode breakeven,
        # so speculation can never be a sustained slowdown.  Replaces burst
        # (one tick IS multi-token); incompatible with JSON-constrained
        # decoding (FSM state is inherently sequential) — submit() rejects
        # json_format when enabled.
        self.speculative = max(0, int(speculative))
        self.spec_width = max(1, int(spec_width)) if self.speculative else 0
        if self.speculative:
            # each scanned verify step writes K+1 positions and
            # _should_finish reserves N*(K+1)-1 tokens of headroom — a
            # budget near max_seq_len would crash the jitted tick (opaquely)
            # or instantly length-limit every request; fail at load with the
            # same clarity as the other config knobs
            if self.burst * (self.speculative + 1) > self.max_seq_len // 4:
                raise ValueError(
                    f"speculative={self.speculative} x decode_steps="
                    f"{self.burst} too large for max_seq_len="
                    f"{self.max_seq_len}: each tick writes up to "
                    f"decode_steps*(K+1) positions and that many tokens of "
                    f"finish headroom are reserved; keep decode_steps*(K+1) "
                    f"<= max_seq_len // 4 ({self.max_seq_len // 4})"
                )
        # canonical alias for the fused-tick depth + the operator gauges
        # behind tick_stats / /healthz / /metrics
        # (`decode_steps_effective`, `weight_bits`, `upload_overlap_frac`):
        # which decode fast path is ACTUALLY active
        self.decode_steps = self.burst
        self._decode_steps_effective = self.burst
        self._json_downgraded_ticks = 0
        # double-buffered host->device uploads: sampling/block-table arrays
        # re-staged at end-of-iteration while `lookahead` ticks are still in
        # flight, so the next tick's dispatch finds them already committed
        # instead of paying the upload enqueue on the issue path
        self._uploads_prestaged = 0
        self._uploads_issue = 0
        # dominant layer-projection weight width (16/8/4) — int4 grouped
        # quantization (ops/quant.py QTensor4) reads 0.5 bytes/weight
        from ..ops.quant import weight_bits as _weight_bits

        try:
            self.weight_bits = _weight_bits(params)
        except Exception:
            self.weight_bits = 16
        self.spec_drafted = 0  # draft tokens proposed (greedy rows only)
        self.spec_accepted = 0  # draft tokens accepted
        self.spec_ticks_issued = 0  # speculative ticks dispatched
        self.spec_skipped_load = 0  # plain ticks forced by queue pressure
        self.spec_skipped_accept = 0  # plain ticks forced by the controller
        self._spec_probe_every = max(1, int(spec_probe_every))
        self._spec_explore_every = max(1, int(spec_explore_every))
        # Prefix KV cache: K/V of shared prompt prefixes (system + packed RAG
        # context) are kept on device and re-inserted into slots instead of
        # being re-prefilled — the reference re-sends and recomputes that
        # context EVERY turn (assistant/bot/services/context_service/steps/
        # final_prompt.py:14).  LRU over at most `prefix_cache_size` prefixes
        # of >= `prefix_min_tokens` tokens; 0 disables the path (and its
        # warmup compiles).
        self.prefix_cache_size = max(0, int(prefix_cache_size))
        self.prefix_min_tokens = max(1, int(prefix_min_tokens))
        # Hard HBM budget for pinned prefix K/V: entries evict (LRU) until the
        # total fits.  Without it, long shared contexts on a deep model pin
        # multi-GB of cache next to the weights (e.g. 8B/32L/8KV/128D bf16 at
        # pb=8192 is ~1 GB per entry).
        self.prefix_cache_max_bytes = int(prefix_cache_max_bytes)
        self._prefix_lru: "collections.OrderedDict[tuple, _Prefix]" = (
            collections.OrderedDict()
        )
        self._prefix_bytes = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        # Mesh-scoped serving (TP/DP): the KV cache shards over the mesh (kv_heads →
        # `model`, slots → `data` — llama.CACHE_AXES) and every device step is jit'd
        # with explicit cache out_shardings so donation updates shards in place.
        # Without it a v5e-8 would hold 8 *replicas* of a multi-GB cache.
        # Reduced-precision slot cache: "fp8" halves KV bytes (the dominant
        # HBM consumer after the weights at long context) — K/V convert to
        # fp8 at cache-write and upcast inside the attention dot at read.
        # Lossy (~2 significand bits): opt-in per model.
        if kv_cache_dtype not in KV_CACHE_DTYPES:
            raise ValueError(
                f"unknown kv_cache_dtype {kv_cache_dtype!r}; "
                f"expected one of {sorted(k for k in KV_CACHE_DTYPES if k)}"
            )
        self.kv_cache_dtype = KV_CACHE_DTYPES[kv_cache_dtype]
        # Length-aware decode attention: read the slot cache in `decode_kv_chunk`
        # -wide slices and skip chunks past the batch's max valid position
        # (models/llama.decode_step kv_chunk -> ops/attention.
        # chunked_gqa_decode_attention).  0 = auto (largest of 512/256/128 that
        # divides max_seq_len, when that leaves >= 2 chunks); None disables —
        # the full-cache read.  The per-tick fraction actually read is tracked
        # host-side and reported as ``kv_read_frac`` in :meth:`tick_stats`.
        self.decode_kv_chunk = self._resolve_kv_chunk(decode_kv_chunk)
        self._kv_frac_sum = 0.0
        # --- paged KV memory plane (docs/KV_PAGING.md) ------------------------
        # "paged" (default): the KV cache is a fixed pool of fixed-size pages
        # plus per-slot block tables — requests reserve only
        # ceil((prompt + max_tokens) / page) pages, common prompt prefixes
        # share pages refcounted (copy-on-write at the boundary page), and
        # admission sheds on KV pressure.  "legacy" keeps the contiguous
        # [max_slots, max_seq_len] layout — the rollback / bench-A/B flag.
        # Paged decode is bit-identical to legacy-with-chunked-read (the page
        # IS the chunk), asserted in tests/test_kv_paging.py.
        if kv_layout not in ("paged", "legacy"):
            raise ValueError(
                f"unknown kv_layout {kv_layout!r}; expected 'paged' or 'legacy'"
            )
        # what the config ASKED for — the non-dividing-context fallback below
        # may silently demote paged to legacy, and kv_stats() reports
        # requested vs effective so operators can see a replica running the
        # legacy plane without grepping boot logs.  (Speculative engines run
        # paged natively since the tree-verify rewrite: the accepted path
        # commits through the block table — commit_tree_path_paged.)
        self.kv_layout_requested = kv_layout
        self.paged = kv_layout == "paged"
        self.kv_page_size = 0
        self._kv_blocks = 0
        self._kv_pool = None
        self._kv_host = None
        # host-tier restore bookkeeping: counters + a bounded window of
        # restore DISPATCH times (host fetch + upload issue — the async
        # restore's host-visible cost; the device overlap hides the rest)
        self.kv_restores = 0
        self.kv_host_hits = 0
        self._kv_restores_inflight = 0
        self._restore_s: "collections.deque[float]" = collections.deque(maxlen=512)
        # fleet prefix listener (router-owned registry): tier-transition
        # events forward here AFTER the engine's own flight recording
        self._prefix_listener: Optional[Callable[..., None]] = None
        if self.paged:
            page = int(kv_page_size) or self.decode_kv_chunk or 0
            if not page:
                # decode_kv_chunk disabled: pick the largest page that still
                # divides the context into >= 2 pages (the paged read is
                # inherently page-chunked — there is no "full read" layout)
                for c in (512, 256, 128, 64, 32, 16, 8):
                    if self.max_seq_len % c == 0 and self.max_seq_len // c >= 2:
                        page = c
                        break
            if not page or self.max_seq_len % page or self.max_seq_len // page < 2:
                logger.warning(
                    "kv_layout='paged' needs a page size dividing "
                    "max_seq_len=%d into >= 2 pages (got %s); falling back to "
                    "the legacy slot cache",
                    self.max_seq_len,
                    page or None,
                )
                self.paged = False
            else:
                self.kv_page_size = page
                self._kv_blocks = self.max_seq_len // page
                n_pages = int(kv_pages) or self.max_slots * self._kv_blocks
                if n_pages < self._kv_blocks:
                    raise ValueError(
                        f"kv_pages={n_pages} cannot hold even one max-length "
                        f"request ({self._kv_blocks} pages of {page})"
                    )
                import jax.numpy as _jnp

                from .kv_pool import PageAllocator

                kv_itemsize = _jnp.dtype(
                    self.kv_cache_dtype or cfg.dtype
                ).itemsize
                page_bytes = (
                    cfg.num_layers
                    * cfg.num_kv_heads
                    * page
                    * cfg.head_dim
                    * 2  # K and V
                    * kv_itemsize
                )
                # --- host KV tier (docs/KV_PAGING.md "Tiered KV") ---------
                # kv_host_bytes > 0 (or a spill dir) arms the durability
                # tier: evicted/registered prefixes keep a host-DRAM copy
                # (then disk), admission restores them into fresh pages ahead
                # of the suffix prefill, and crash-only _restart re-seeds
                # warm sessions from here instead of losing them.
                import os as _os

                from .kv_pool import HostKVTier

                spill_dir = kv_spill_dir or _os.environ.get(
                    "DABT_KV_SPILL_DIR", ""
                ).strip() or None
                host_tier = None
                if int(kv_host_bytes) > 0 or spill_dir:
                    host_tier = HostKVTier(
                        # a spill dir alone gets a small DRAM staging budget
                        # (entries flow through host DRAM on their way down)
                        int(kv_host_bytes) or 64 * page_bytes,
                        page_size=page,
                        page_bytes=page_bytes,
                        spill_dir=spill_dir,
                        name=f"{name}-kv-host",
                    )
                self._kv_host = host_tier
                # the r4 prefix-LRU knobs map straight onto the page pool:
                # entry count -> registry entries, byte budget -> shared-page
                # budget, min tokens -> registration threshold
                self._kv_pool = PageAllocator(
                    n_pages,
                    page,
                    page_bytes=page_bytes,
                    max_shared_bytes=self.prefix_cache_max_bytes,
                    max_shared_entries=self.prefix_cache_size,
                    min_prefix_tokens=self.prefix_min_tokens,
                    host_tier=host_tier,
                    writethrough=bool(kv_host_writethrough),
                )
                self._kv_pool.bind_spill_fetch(self._fetch_pages_host)
                self._kv_pool.on_event = self._on_kv_tier_event
                if host_tier is not None:
                    host_tier.on_event = self._on_kv_tier_event
                self._kv_sentinel = n_pages  # block-table "unallocated" marker
        # --- continuous batching: piggybacked chunked prefill ----------------
        # One jitted program runs a bounded prefill chunk for the admitting
        # slot AND the fused decode scan for resident slots per dispatch, so
        # a long prompt stops displacing decode ticks (ROADMAP item 2).
        # Token-identical to the sequential chunk-then-tick path: the chunk
        # consumes no rng, writes only its own slot's pages/rows, and runs
        # before the decode scan inside the program — the same order the
        # sequential loop executes them.  prefill_piggyback=False is the
        # one-flag rollback (and the bench A/B off-arm).
        self.prefill_piggyback = bool(prefill_piggyback)
        # fp8 in-dot attention (docs/QUANT.md): keep the fp8 KV read operand
        # at storage width through the decode attention dots.  Requires an
        # fp8 cache; the legacy layout additionally needs the chunked read
        # (the full-cache gqa path has no in-dot scheme).
        self.attn_fp8 = bool(attn_fp8)
        if self.attn_fp8:
            import jax.numpy as _jnp

            kv_dt = self.kv_cache_dtype
            if kv_dt is None or _jnp.dtype(kv_dt).itemsize != 1:
                raise ValueError(
                    "attn_fp8=True requires an fp8 KV cache "
                    "(kv_cache_dtype='fp8' or 'fp8_e5m2')"
                )
            if not self.paged and not self.decode_kv_chunk:
                raise ValueError(
                    "attn_fp8=True on the legacy KV layout requires the "
                    "chunked decode read (decode_kv_chunk != None)"
                )
        # Admission-controlled scheduling (serving/scheduler.py): when present,
        # submit() runs its admission test (bounded queue, estimated wait) and
        # _admit pulls requests in weighted-fair-share order instead of FIFO.
        # None = the legacy unbounded FIFO path (kept as the baseline the
        # overload bench compares against).
        self.scheduler = scheduler
        if scheduler is not None:
            scheduler.bind_slots(max_slots)
            if self._kv_pool is not None:
                # KV-pressure admission: the scheduler compares a request's
                # projected page demand against the pool's obtainable pages
                # (free + evictable cached prefixes) minus what the queue has
                # already reserved — shedding with its own 429 reason instead
                # of queueing work the pool cannot place (docs/SCHEDULING.md)
                scheduler.bind_kv(
                    self._kv_pool.available, self._kv_pool.n_pages
                )
                if self._kv_host is not None:
                    # host/disk-tier gauges ride in the scheduler's stats()
                    # block so operators (and the autoscaler) read pool
                    # pressure and warm-tier depth side by side
                    scheduler.bind_kv_tier(self._kv_host.stats)
            if self.obs is not None:
                # predictive admission (docs/AUTOSCALING.md): once warm, the
                # obs plane's queue-wait histogram floors the estimated-wait
                # model with the measured tail of realized waits, and the 429
                # Retry-After becomes that prediction instead of a heuristic
                scheduler.bind_wait_hist(self.obs.queue_wait_s)
        # --- supervision (docs/RESILIENCE.md) ---------------------------------
        # Deterministic fault injection (serving/faults.py).  None = off: the
        # hot path pays one `is None` check per tick, nothing else.
        self._faults = faults
        # Loop errors are classified request-poison (quarantine one slot) vs
        # engine-fatal (crash-only restart: rebuild device state, salvage
        # work).  Restarts back off exponentially, and max_restarts inside
        # restart_window_s opens a circuit: submit() fast-fails
        # EngineUnavailable until degraded_cooldown_s elapses (half-open).
        self.max_restarts = max(1, int(max_restarts))
        self.restart_window_s = float(restart_window_s)
        self.restart_backoff_s = max(0.0, float(restart_backoff_s))
        self.restart_backoff_max_s = max(
            self.restart_backoff_s, float(restart_backoff_max_s)
        )
        self.degraded_cooldown_s = max(0.0, float(degraded_cooldown_s))
        self.heartbeat_degraded_s = max(0.1, float(heartbeat_degraded_s))
        self.max_request_restarts = max(0, int(max_request_restarts))
        self.engine_restarts = 0
        self.poisoned_requests = 0
        self.circuit_trips = 0
        self.restarted_resubmitted = 0
        self.restarted_failed = 0
        self._restart_times: "collections.deque[float]" = collections.deque(maxlen=64)
        self._consecutive_failures = 0
        self._degraded_until: Optional[float] = None
        # loop heartbeat: stamped at the top of every loop iteration so a
        # wedged engine thread (stuck XLA call) is visible as a growing
        # loop_heartbeat_age_s in /healthz instead of stale-but-green stats
        self._beat = self._clock()
        # live slots reclaimed before finishing (expired deadline / client
        # cancel) — each one freed mid-decode instead of burning ticks
        self.reclaimed_slots = 0
        # the client-cancel subset of the above: a streaming consumer that
        # disconnected mid-generation (its iterator cancelled the future) —
        # the disconnect-reaping evidence /healthz and tick_stats expose
        self.cancelled_slots = 0
        # perceived-latency samples, host-side: TTFT (submit -> first token on
        # host) and inter-token gaps as _process_tick consumes device results.
        # Bounded windows; read via latency_stats()/tick_stats()/healthz.
        self._ttft_s: "collections.deque[float]" = collections.deque(maxlen=1024)
        self._itl_s: "collections.deque[float]" = collections.deque(maxlen=4096)
        # streams owed a wakeup, flushed at the end of each _process_tick:
        # one cross-thread notify per stream per tick, delivered just before
        # the engine thread returns to device work (engine-thread-only state)
        self._stream_notify: set = set()
        self.mesh = mesh
        if mesh is not None:
            self._cache_shardings = (
                llama.paged_cache_shardings(cfg, mesh, max_slots)
                if self.paged
                else llama.cache_shardings(cfg, mesh, max_slots)
            )
        else:
            self._cache_shardings = None
        # --- mesh-sliced fleet identity (parallel/slicing.py;
        # docs/MULTICHIP.md) ------------------------------------------------
        # slice_id/release_slice are set by the registry when this replica is
        # pinned to its own device slice; slice_devices is derived from
        # whatever mesh THIS engine actually traces onto, so the gauge can
        # never disagree with placement.  The per-slice HBM ledger below is
        # the operator evidence that a replica's footprint lives only on its
        # slice: device-RESIDENT bytes (one entry per addressable shard, so
        # replication across mesh axes is charged, sharding is not
        # double-charged), computed once here — weights never move and the
        # cache/pool allocation is fixed for the engine's lifetime.
        self.slice_id: Optional[int] = None
        self.release_slice: Optional[Callable[[], None]] = None
        if mesh is not None:
            self.slice_devices = [d.id for d in np.asarray(mesh.devices).flatten()]
        else:
            self.slice_devices = []
        self.hbm_weight_bytes = _resident_bytes(params)

        self._queue: "queue.Queue[_Request]" = queue.Queue()
        self._pending: "collections.deque[_Request]" = collections.deque()
        self._chunking: Optional[_ChunkedPrefill] = None
        # requests currently mid-start (popped from _pending, not yet slotted):
        # must be failed explicitly if their prefill/activation raises
        self._starting_batch: Optional[List[tuple]] = None
        self._slots: List[Optional[_Slot]] = [None] * max_slots
        self._slot_epoch = [0] * max_slots
        self._inflight: "collections.deque[_TickRef]" = collections.deque()
        self._cache = self._fresh_cache()
        # KV side of the per-slice HBM ledger: the paged pool or legacy slot
        # cache allocation (fixed for the engine's lifetime — restarts
        # rebuild the same shape on the same devices)
        self.hbm_kv_bytes = _resident_bytes(self._cache)
        # per-slot block tables (host-owned, paged layout): logical block ->
        # physical page, with n_pages as the "unallocated" sentinel.  Uploaded
        # lazily like the sampling arrays (committed replicated array, re-sent
        # only when admissions/frees change it) — NOT part of the donated
        # cache chain, so host edits never race a device step.
        if self.paged:
            self._slot_pages: List[List[int]] = [[] for _ in range(max_slots)]
            self._block_tables = np.full(
                (max_slots, self._kv_blocks), self._kv_sentinel, np.int32
            )
        else:
            self._slot_pages = []
            self._block_tables = np.zeros((1, 1), np.int32)  # inert legacy stub
        self._bt_dev = jax.device_put(
            jnp.asarray(self._block_tables),
            _replicated(mesh) if mesh is not None else None,
        )
        self._bt_dirty = False
        self._tokens_dev = self._fresh_tokens()
        self._temps = np.zeros((max_slots,), np.float32)
        self._top_ps = np.ones((max_slots,), np.float32)
        self._sampling_dirty = True
        self._temps_dev = None
        self._top_ps_dev = None
        self._active_dev = None
        # grammar-constrained JSON decoding: tables built lazily on first use
        self._json = np.zeros((max_slots,), bool)
        self._json_dev = None
        self._fsm = None  # ops.json_fsm.TokenFSM
        self._fsm_next_dev = None
        self._fsm_allowed_dev = None
        self._fsm_states_dev = self._fresh_tokens()
        self._decode_tick_json = None
        self._reseeds = 0  # distinct recovery seeds even for back-to-back failures
        self._rng = self._fresh_rng(0)
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self.steps = 0
        # Serializes one engine-loop iteration against probe_decode: the probe
        # mutates engine-thread-owned device state (_cache/_tokens_dev/_rng),
        # so it must never interleave with an admission/tick.  Uncontended in
        # normal serving (the loop is the only taker).
        # CALLBACK CONTRACT (dabtlint DABT102 baseline + witness allowlist):
        # futures resolve INSIDE the iteration, so a Future done-callback runs
        # with this lock held — callbacks must therefore never acquire any
        # engine's _iter_lock (router re-dispatch takes router/scheduler
        # locks and the TARGET engine's submit queue only; idle() is the one
        # _iter_lock taker outside the loop and resolves nothing).  See
        # docs/STATIC_ANALYSIS.md.
        self._iter_lock = threading.Lock()
        # Per-tick wall breakdown (engine thread only): where a decode token's
        # time actually goes — `issue_s` is dispatch enqueue (host->device RPC
        # under a tunnel), `block_s` is waiting on a tick's sampled ids in
        # _process_tick, everything else is host bookkeeping.  Read via
        # :meth:`tick_stats`; the roofline work (VERDICT r3 weak #2) tunes
        # burst/slots from these instead of guessing.
        self._tick_issue_s = 0.0
        self._tick_block_s = 0.0
        self._ticks_issued = 0
        self._ticks_processed = 0

        cfg_c = cfg
        self._decode_tick = self._make_decode_tick(json_mode=False)
        # continuous-batching program: prefill chunk + decode scan fused into
        # one dispatch.  Speculative engines keep sequential chunking (the
        # spec tick owns the token/history chain the piggyback scan would
        # fork); the knob is the rollback/A-B flag.
        self._piggyback_tick = (
            self._make_piggyback_tick()
            if self.prefill_piggyback and not self.speculative
            else None
        )
        self._prefill_displaced_ticks = 0
        self._prefill_chunks_piggybacked = 0
        self._activate_fn = self._make_activate(json_mode=False)
        self._activate_fn_json = None  # built in _ensure_fsm
        self._spec_ticks: Dict[tuple, Any] = {}
        self._spec_ctl = None
        self._history_dev = self._fresh_history() if self.speculative else None
        if self.speculative:
            from ..ops.speculative import SpecController, default_rungs

            # one compiled program per rung of the controller's shrink
            # ladder; the controller switches between them per tick (the
            # tree SHAPE is static inside each program)
            self._spec_ctl = SpecController(
                rungs=default_rungs(self.spec_width, self.speculative),
                probe_every=self._spec_probe_every,
                explore_every=self._spec_explore_every,
            )
            for rung in self._spec_ctl.rungs:
                self._spec_ticks[rung] = self._make_spec_tick(*rung)
            if scheduler is not None:
                # load-disable vs acceptance-disable, side by side in the
                # scheduler's own stats: operators watching the degradation
                # band can tell which mechanism turned speculation off
                scheduler.bind_spec(self._spec_disabled_gauge)
            rep = _replicated(self.mesh) if self.mesh is not None else None
            self._hist_set = jax.jit(
                lambda h, row, slot: jax.lax.dynamic_update_slice(
                    h, row[None], (slot, 0)
                ),
                donate_argnums=(0,),
                out_shardings=rep,
            )

        if mesh is not None:
            insert_out = self._cache_shardings
            chunk_out = (_replicated(mesh), self._cache_shardings)
        else:
            insert_out = chunk_out = None

        def _prefill(params, ids, lengths):
            return llama.prefill(params, cfg_c, ids, lengths)

        self._prefill = jax.jit(_prefill)
        # donate the cache here too: slot insertion is a scatter into HBM, not a copy
        if self.paged:
            self._insert = jax.jit(
                llama.insert_sequences_paged,
                donate_argnums=(0,),
                out_shardings=insert_out,
            )

            def _prefill_chunk_paged(params, ids, cache, bt_row, slot, start, valid):
                return llama.prefill_chunk_paged(
                    params, cfg_c, ids, cache, bt_row, slot, start, valid
                )

            self._prefill_chunk = jax.jit(
                _prefill_chunk_paged, donate_argnums=(2,), out_shardings=chunk_out
            )

            def _prefill_suffix_paged(params, ids, cache, bt, slots, starts, valids):
                return llama.prefill_suffix_paged(
                    params, cfg_c, ids, cache, bt, slots, starts, valids
                )

            suffix_out = (
                (_replicated(mesh), self._cache_shardings)
                if mesh is not None
                else None
            )
            self._prefill_suffix = jax.jit(
                _prefill_suffix_paged, donate_argnums=(2,), out_shardings=suffix_out
            )
            # the allocator's COW primitive: clone the boundary page a prefix
            # sharer will write its own suffix into
            self._copy_pages = jax.jit(
                llama.copy_pages, donate_argnums=(0,), out_shardings=insert_out
            )
            # host-tier spill/restore primitives (docs/KV_PAGING.md "Tiered
            # KV").  The gather does NOT donate the cache — it is a read-only
            # device->host copy off the hot path (the spill side); the write
            # donates like every other cache mutation (the restore side: the
            # upload is dispatched ahead of the slot's suffix prefill and the
            # device stream orders them, so admission never blocks on it).
            def _gather_pages(cache, idx):
                return (
                    jnp.take(cache.k, idx, axis=1),
                    jnp.take(cache.v, idx, axis=1),
                )

            gather_out = (
                (_replicated(mesh), _replicated(mesh)) if mesh is not None else None
            )
            self._gather_pages = jax.jit(_gather_pages, out_shardings=gather_out)

            def _write_pages(cache, idx, k, v):
                return llama.PagedKVCache(
                    k=cache.k.at[:, idx].set(k.astype(cache.k.dtype)),
                    v=cache.v.at[:, idx].set(v.astype(cache.v.dtype)),
                    lengths=cache.lengths,
                )

            self._write_pages = jax.jit(
                _write_pages, donate_argnums=(0,), out_shardings=insert_out
            )
            self._insert_prefix = self._extract_prefix = None
        else:
            self._insert = jax.jit(
                llama.insert_sequences, donate_argnums=(0,), out_shardings=insert_out
            )

            def _prefill_chunk(params, ids, cache, slot, start, valid):
                return llama.prefill_chunk(params, cfg_c, ids, cache, slot, start, valid)

            self._prefill_chunk = jax.jit(
                _prefill_chunk, donate_argnums=(2,), out_shardings=chunk_out
            )

            def _prefill_suffix(params, ids, cache, slots, starts, valids):
                return llama.prefill_suffix(params, cfg_c, ids, cache, slots, starts, valids)

            if mesh is not None:
                pfx = llama.prefix_shardings(cfg, mesh)
                suffix_out = (_replicated(mesh), self._cache_shardings)
                extract_out = (pfx, pfx)
            else:
                suffix_out = extract_out = None
            self._prefill_suffix = jax.jit(
                _prefill_suffix, donate_argnums=(2,), out_shardings=suffix_out
            )
            self._insert_prefix = jax.jit(
                llama.insert_prefix, donate_argnums=(0,), out_shardings=insert_out
            )
            self._extract_prefix = jax.jit(
                llama.extract_prefix, static_argnums=(2,), out_shardings=extract_out
            )
            self._copy_pages = None
            self._gather_pages = self._write_pages = None

    def _make_activate(self, json_mode: bool):
        """Build the jitted activation: mask (JSON), sample the first token per
        row, scatter into the decode token array (pad/non-JSON rows drop via
        out-of-bounds indices), and advance FSM states.  One fused program per
        batch bucket — eagerly composing these ops would pay a compile round
        trip PER OP under a remote device."""
        from ..ops.attention import NEG_INF

        top_k_c = self.top_k
        oob = self.max_slots  # out-of-bounds scatter index -> mode="drop"

        def act(logits, tokens_dev, rng, temps, top_ps, scatter_idx,
                fsm_states=None, jmask=None, init_row=None, next_tab=None,
                initial=None):
            rng, sub = jax.random.split(rng)
            if json_mode:
                logits = jnp.where(
                    jmask[:, None] & ~init_row[None, :], NEG_INF, logits
                )
            first = sample_logits(
                logits, sub, temperature=temps, top_k=top_k_c, top_p=top_ps
            )
            tokens_dev = tokens_dev.at[scatter_idx].set(first, mode="drop")
            if json_mode:
                safe = jnp.minimum(first, next_tab.shape[1] - 1)
                new_states = next_tab[initial, safe]
                fsm_idx = jnp.where(jmask, scatter_idx, oob)
                fsm_states = fsm_states.at[fsm_idx].set(new_states, mode="drop")
                return first, tokens_dev, rng, fsm_states
            return first, tokens_dev, rng

        if self.mesh is not None:
            rep = _replicated(self.mesh)
            out = (rep, rep, rep) + ((rep,) if json_mode else ())
        else:
            out = None
        return jax.jit(act, out_shardings=out, static_argnames=("initial",))

    def _make_decode_tick(self, json_mode: bool, steps: Optional[int] = None):
        """Build the jitted fused tick: ``steps`` chained decode steps in one
        dispatch -> (toks [K,B], last tokens [B], cache[, fsm states]).

        ``steps`` defaults to the engine's ``decode_steps``; the JSON variant
        is built with ``steps=1`` — fused ticks are disabled while json_fsm
        slots are live (the FSM advance stays on-device either way, but
        keeping constrained decoding on the single-step program means its
        semantics never ride a multi-step scan and a mixed batch degrades
        predictably — ``decode_steps_effective`` reports the downgrade).
        ``json_mode`` adds the grammar mask before sampling and the FSM
        advance after it (trace-time branches, so the plain path pays nothing
        for them).  The cache (argnum 2) is donated — in-place HBM update,
        no copy."""
        from ..ops.attention import NEG_INF

        cfg_c, top_k_c = self.cfg, self.top_k
        burst_c = int(steps) if steps is not None else self.burst
        kv_chunk_c = self.decode_kv_chunk
        paged_c = self.paged
        fp8_c = self.attn_fp8

        def tick(params, tokens, cache, active, bt, temps, top_ps, rng,
                 fsm_s=None, jmask=None, next_tab=None, allowed_tab=None):
            def body(carry, _):
                tokens, cache, rng, fsm_s = carry
                # The params are invariant across the burst scan, so XLA's
                # loop-invariant code motion will HOIST their dequantization
                # out of the loop — materializing a full bf16 copy of every
                # int8 weight (2x HBM: an 8B int8 model OOMs a 16 GB chip at
                # compile, and a 1B model silently reads bf16-sized traffic,
                # erasing the int8 bandwidth win).  The barrier pins the
                # weights inside the body: dequant stays per-layer-slice.
                # At burst=1 there is no loop to hoist out of and the barrier
                # is pure cost (it can force program-local weight copies) —
                # skip it.
                p = jax.lax.optimization_barrier(params) if burst_c > 1 else params
                rng, sub = jax.random.split(rng)
                if paged_c:
                    logits, cache = llama.decode_step_paged(
                        p, cfg_c, tokens, cache, bt, active=active,
                        attn_fp8=fp8_c,
                    )
                else:
                    logits, cache = llama.decode_step(
                        p, cfg_c, tokens, cache, active=active,
                        kv_chunk=kv_chunk_c, attn_fp8=fp8_c,
                    )
                if json_mode:
                    ok = allowed_tab[fsm_s]  # [B, V]
                    logits = jnp.where(jmask[:, None] & ~ok, NEG_INF, logits)
                nxt = sample_logits(
                    logits, sub, temperature=temps, top_k=top_k_c, top_p=top_ps
                )
                if json_mode:
                    safe = jnp.minimum(nxt, next_tab.shape[1] - 1)
                    fsm_s = jnp.where(jmask, next_tab[fsm_s, safe], fsm_s)
                return (nxt, cache, rng, fsm_s), nxt

            carry = (tokens, cache, rng, fsm_s if json_mode else jnp.zeros_like(tokens))
            if burst_c == 1:
                # No scan wrapper: at flagship (8B) geometry the scanned tick's
                # compiled scratch is what tips a shared chip into OOM — the
                # unrolled single step compiles with the same footprint as the
                # plain decode_step program.
                carry, tok = body(carry, None)
                tokens, cache, rng, fsm_s = carry
                toks = tok[None]
            else:
                (tokens, cache, rng, fsm_s), toks = jax.lax.scan(
                    body, carry, None, length=burst_c
                )
            # the advanced rng is an output: the host threads it call-to-call as
            # opaque device state — an eager jax.random.split per burst would be
            # one more dispatch round trip on the critical host path
            if json_mode:
                return toks, tokens, cache, rng, fsm_s
            return toks, tokens, cache, rng

        if self.mesh is not None:
            rep = _replicated(self.mesh)
            out = (rep, rep, self._cache_shardings, rep) + ((rep,) if json_mode else ())
        else:
            out = None
        return jax.jit(tick, donate_argnums=(2,), out_shardings=out)

    def _make_piggyback_tick(self):
        """Continuous-batching tick: ONE jitted program runs a bounded prefill
        chunk for the admitting slot AND the fused ``decode_steps`` scan for
        the resident slots (ROADMAP item 2 "chunked prefill piggybacked into
        the fused decode tick").

        Token-identity with the sequential chunk-then-tick path holds by
        construction: the chunk runs FIRST inside the program (the order the
        sequential loop executes them), consumes no rng, and touches only the
        admitting slot's pages/row — which the decode reads never visit (the
        admitting slot is not yet active, and shared prefix pages are never
        in the chunk's write window: the chunk starts past the shared prefix,
        boundary page COW-cloned at admission).  The decode scan body is the
        same computation as :meth:`_make_decode_tick`'s over the same
        operands, so sampled ids match bit-for-bit (pinned by
        tests/test_contbatch.py).  JSON-constrained and speculative ticks
        never piggyback (host-side gate in the loop)."""
        from ..ops.attention import NEG_INF  # noqa: F401 (parity with decode tick)

        cfg_c, top_k_c = self.cfg, self.top_k
        burst_c = self.burst
        kv_chunk_c = self.decode_kv_chunk
        paged_c = self.paged
        fp8_c = self.attn_fp8

        def tick(params, tokens, cache, active, bt, temps, top_ps, rng,
                 c_ids, c_slot, c_start, c_valid):
            # --- the piggybacked prefill chunk (admitting slot only) -------
            if paged_c:
                bt_row = jax.lax.dynamic_index_in_dim(bt, c_slot, 0, keepdims=False)
                _, cache = llama.prefill_chunk_paged(
                    params, cfg_c, c_ids, cache, bt_row, c_slot, c_start, c_valid
                )
            else:
                _, cache = llama.prefill_chunk(
                    params, cfg_c, c_ids, cache, c_slot, c_start, c_valid
                )

            # --- the fused decode scan (resident slots) --------------------
            def body(carry, _):
                tokens, cache, rng = carry
                p = jax.lax.optimization_barrier(params) if burst_c > 1 else params
                rng, sub = jax.random.split(rng)
                if paged_c:
                    logits, cache = llama.decode_step_paged(
                        p, cfg_c, tokens, cache, bt, active=active,
                        attn_fp8=fp8_c,
                    )
                else:
                    logits, cache = llama.decode_step(
                        p, cfg_c, tokens, cache, active=active,
                        kv_chunk=kv_chunk_c, attn_fp8=fp8_c,
                    )
                nxt = sample_logits(
                    logits, sub, temperature=temps, top_k=top_k_c, top_p=top_ps
                )
                return (nxt, cache, rng), nxt

            carry = (tokens, cache, rng)
            if burst_c == 1:
                carry, tok = body(carry, None)
                tokens, cache, rng = carry
                toks = tok[None]
            else:
                (tokens, cache, rng), toks = jax.lax.scan(
                    body, carry, None, length=burst_c
                )
            return toks, tokens, cache, rng

        if self.mesh is not None:
            rep = _replicated(self.mesh)
            out = (rep, rep, self._cache_shardings, rep)
        else:
            out = None
        return jax.jit(tick, donate_argnums=(2,), out_shardings=out)

    def _ensure_fsm(self):
        """Build the JSON token-FSM tables on first constrained request (one-time:
        char DFA + vectorised closure over the tokenizer) and the json tick jit."""
        if self._fsm is not None:
            return
        from ..ops.json_fsm import fsm_for_tokenizer

        fsm = fsm_for_tokenizer(self.tokenizer)
        V_model = self.cfg.vocab_size
        S, V_tok = fsm.allowed.shape
        # pad to the model vocab: ids beyond the tokenizer are never valid JSON
        allowed = np.zeros((S, V_model), bool)
        allowed[:, : min(V_tok, V_model)] = fsm.allowed[:, :V_model]
        nxt = np.full((S, V_model), fsm.dead, np.int32)
        nxt[:, : min(V_tok, V_model)] = fsm.next_state[:, :V_model]
        self._fsm = fsm
        rep = _replicated(self.mesh) if self.mesh is not None else None
        self._fsm_allowed_dev = jax.device_put(allowed, rep)
        self._fsm_next_dev = jax.device_put(nxt, rep)
        self._fsm_init_row_dev = jax.device_put(allowed[fsm.initial], rep)
        # json ticks are single-step: fused (N-step) decoding is disabled
        # whenever a json_fsm slot is live (see _make_decode_tick)
        self._decode_tick_json = self._make_decode_tick(json_mode=True, steps=1)
        self._activate_fn_json = self._make_activate(json_mode=True)

    def _fresh_rng(self, seed: int) -> jnp.ndarray:
        """Committed-sharding rng key — the rng threads through jit outputs and
        must round-trip with the exact sharding the programs emit (see
        :meth:`_fresh_tokens`)."""
        return jax.device_put(
            jax.random.key(seed),
            _replicated(self.mesh) if self.mesh is not None else None,
        )

    def _fresh_tokens(self) -> jnp.ndarray:
        """Zeroed [max_slots] int32 with the SAME committed sharding the jitted
        steps emit — warmup and serving must present identical input shardings
        or the fused programs silently recompile at serve time."""
        z = jnp.zeros((self.max_slots,), jnp.int32)
        if self.mesh is not None:
            return jax.device_put(z, _replicated(self.mesh))
        return jax.device_put(z)

    def _fresh_history(self):
        """Zeroed [max_slots, max_seq_len] int32 device token history (the
        prompt-lookup draft source), replicated like the token array."""
        z = jnp.zeros((self.max_slots, self.max_seq_len), jnp.int32)
        if self.mesh is not None:
            return jax.device_put(z, _replicated(self.mesh))
        return jax.device_put(z)

    def _make_spec_tick(self, width: int, depth: int, steps: Optional[int] = None):
        """Fused tree-speculative tick for one (width, depth) rung: on-device
        n-gram TREE draft -> one read-only verify forward over every node
        (ancestor-masked) -> longest root-to-leaf acceptance -> accepted-path
        K/V commit (contiguous write on the legacy layout; drop-masked
        block-table scatter on the paged plane) -> history/length update —
        all chained device state (lookahead-compatible; zero host round trips
        per tick).  See ops/speculative.py for the acceptance semantics and
        models/llama.verify_tree_step for the forward.

        Spec x fused composition (docs/SPECULATIVE.md): a verify step IS a
        multi-token tick, so ``decode_steps`` scans N whole
        draft->verify->accept->commit passes into ONE dispatch — the same
        program family (and the same optimization-barrier discipline) as the
        plain fused tick, with the rung ladder choosing the tree shape per
        dispatch.  Outputs are stacked per step: ``toks [N, K+1, B]`` /
        ``n_new [N, B]`` (N = 1 included, so the host consumer has one
        shape contract)."""
        from ..ops.speculative import (
            accept_tree,
            build_tree_draft,
            flatten_tree,
            make_tree_spec,
        )

        cfg_c, top_k_c, K = self.cfg, self.top_k, int(depth)
        N = int(width)
        S = self.max_seq_len
        steps_c = int(steps) if steps is not None else self.burst
        spec = make_tree_spec(N, K)
        depths_c = jnp.asarray(spec.depths)
        anc_c = jnp.asarray(spec.anc_mask)
        paged_c = self.paged

        def tick(params, tokens, history, cache, bt, active, temps, top_ps, rng):
            def body(carry, _):
                tokens, history, cache, rng = carry
                # same anti-hoisting barrier as the fused decode scan: keep
                # the weights' dequantization inside the scanned body
                p = jax.lax.optimization_barrier(params) if steps_c > 1 else params
                draft = build_tree_draft(history, cache.lengths, tokens, N, K)
                tree = flatten_tree(tokens, draft)  # [B, 1 + N*K]
                if paged_c:
                    logits, tks, tvs = llama.verify_tree_step_paged(
                        p, cfg_c, tree, cache, bt, depths_c, anc_c
                    )
                else:
                    logits, tks, tvs = llama.verify_tree_step(
                        p, cfg_c, tree, cache, depths_c, anc_c
                    )
                out, n_new, bonus, path_idx, rng = accept_tree(
                    logits, tree, spec, rng,
                    temperature=temps, top_k=top_k_c, top_p=top_ps,
                )
                n_new = jnp.where(active, n_new, 0)
                if paged_c:
                    # accepted-prefix-only commit: everything past the
                    # accepted run (and every inactive row) drops at the page
                    # sentinel — a paged garbage write could land in a page
                    # since handed to another request, so masking is part of
                    # the contract
                    cache = llama.commit_tree_path_paged(
                        cache, tks, tvs, path_idx, bt, n_new, active
                    )
                else:
                    # contiguous rows tolerate the rejected tail: it sits
                    # past the new valid length, masked/overwritten like all
                    # garbage
                    cache = llama.commit_tree_path(cache, tks, tvs, path_idx)
                # persist this step's input token + accepted tokens into the
                # history at sequence positions lengths..lengths+K+1;
                # positions beyond the accepted run hold garbage that later
                # steps overwrite (exactly the KV-cache discipline), and the
                # draft search never reads past the valid length
                row_tokens = jnp.concatenate([tokens[:, None], out], axis=1)
                # gather+where instead of a vmapped dynamic_update_slice: the
                # per-row scatter that vmap lowers to trips this jaxlib's HLO
                # verifier (broadcast rank RET_CHECK) on CPU; the masked
                # gather writes the identical window and lowers everywhere
                pos = jnp.minimum(cache.lengths, S - (K + 2))  # [B]
                rel = jnp.arange(S)[None, :] - pos[:, None]  # [B,S]
                in_window = (rel >= 0) & (rel < K + 2)
                gathered = jnp.take_along_axis(
                    row_tokens, jnp.clip(rel, 0, K + 1), axis=1
                )
                upd = jnp.where(in_window, gathered, history)
                history = jnp.where(active[:, None], upd, history)
                new_len = jnp.where(
                    active, jnp.minimum(cache.lengths + n_new, S), cache.lengths
                )
                cache = cache._replace(lengths=new_len.astype(cache.lengths.dtype))
                tokens = jnp.where(active, bonus, tokens)
                return (tokens, history, cache, rng), (out.T, n_new)

            carry = (tokens, history, cache, rng)
            if steps_c == 1:
                # no scan wrapper at depth 1 (the OOM discipline of
                # _make_decode_tick): unrolled, then stacked to the [1, ...]
                # shape contract
                carry, (tok, n_new) = body(carry, None)
                tokens, history, cache, rng = carry
                toks, n_news = tok[None], n_new[None]
            else:
                (tokens, history, cache, rng), (toks, n_news) = jax.lax.scan(
                    body, carry, None, length=steps_c
                )
            return toks, n_news, tokens, history, cache, rng

        if self.mesh is not None:
            rep = _replicated(self.mesh)
            out_sh = (rep, rep, rep, rep, self._cache_shardings, rep)
        else:
            out_sh = None
        return jax.jit(tick, donate_argnums=(2, 3), out_shardings=out_sh)

    def _fresh_cache(self):
        dt = self.kv_cache_dtype
        if self.paged:
            n_pages, page = self._kv_pool.n_pages, self.kv_page_size

            def make():
                return llama.init_paged_cache(
                    self.cfg, self.max_slots, n_pages, page, dtype=dt
                )
        else:
            def make():
                return llama.init_cache(
                    self.cfg, self.max_slots, self.max_seq_len, dtype=dt
                )

        if self._cache_shardings is not None:
            # Allocate *sharded*: an eager init_cache would materialise the whole
            # cache on device 0 first — at slice-sized caches that alone overflows
            # one chip's HBM.
            with self.mesh:
                return jax.jit(make, out_shardings=self._cache_shardings)()
        return make()

    def _mesh_scope(self):
        """Trace/run device steps inside the mesh so sharding constraints bind."""
        return self.mesh if self.mesh is not None else contextlib.nullcontext()

    # ------------------------------------------------------------------ public
    def start(self) -> "GenerationEngine":
        if self._running:
            return self
        if self._thread is not None and self._thread.is_alive():
            # a deadline-expired stop() left the old loop draining (stuck in an
            # XLA call); a second loop would race it over engine-private state
            raise RuntimeError(
                "previous engine thread is still draining; cannot restart yet"
            )
        self._running = True
        self._beat = self._clock()
        self._thread = threading.Thread(target=self._loop, daemon=True, name="gen-engine")
        self._thread.start()
        return self

    def stop(self, drain_timeout_s: float = 120.0):
        """Stop the engine and fail unfinished requests.

        The engine thread drains its own private state (slots, pending queue)
        when its loop exits — ``stop`` only waits for that, bounded by
        ``drain_timeout_s``.  A first-call XLA compile can hold a device step
        for minutes; past the deadline we dump the engine thread's stack (so
        a hung drain is diagnosable from the log alone) and return — the
        daemon thread finishes the drain itself when the in-flight call
        returns, so no future is ever left dangling."""
        self._running = False
        t = self._thread
        if t is not None:
            start = self._clock()
            deadline = start + drain_timeout_s
            t.join(timeout=min(5.0, drain_timeout_s))
            while t.is_alive() and self._clock() < deadline:
                logger.warning(
                    "engine thread still draining (device step or compile in "
                    "flight); %.0fs elapsed, waiting up to %.0fs",
                    self._clock() - start,
                    drain_timeout_s,
                )
                t.join(timeout=min(15.0, max(0.0, deadline - self._clock())))
            if t.is_alive():
                logger.error(
                    "engine thread did not drain within %.0fs; its requests "
                    "will fail when the in-flight XLA call returns",
                    drain_timeout_s,
                )
                try:  # diagnose the stuck XLA call: where is the thread?
                    import faulthandler
                    import sys

                    faulthandler.dump_traceback(file=sys.stderr)
                except Exception:  # pragma: no cover - diagnostics only
                    pass
            else:
                self._thread = None
        # anything submitted after the loop exited (or with no thread at all)
        self._drain_incoming(RuntimeError("generation engine stopped"))

    def _drain_queue(self, err: BaseException):
        """Fail everything not yet started.  Only called from the engine thread
        itself (end-of-loop _shutdown) — ``_pending``/``_chunking`` are
        engine-thread-private state."""
        if self._chunking is not None:
            _safe_resolve(self._chunking.request.future, exc=err)
            self._chunking = None
        while self._pending:
            _safe_resolve(self._pending.popleft().future, exc=err)
        if self.scheduler is not None:
            self.scheduler.drain(err)
        self._drain_incoming(err)

    def _drain_incoming(self, err: BaseException):
        """Drain the thread-safe submission queue only (safe from any thread)."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            _safe_resolve(req.future, exc=err)

    def submit(
        self,
        prompt_ids: Sequence[int],
        *,
        max_tokens: int = 1024,
        temperature: float = 0.8,
        top_p: float = 0.95,
        json_format: bool = False,
        prefix_len: int = 0,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        stream: Any = None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Thread-safe submission; returns a concurrent Future[GenerationResult].

        ``prefix_len``: the first N prompt tokens are a shared, cacheable
        prefix (identical across requests, e.g. the system + RAG-context block)
        — the engine reuses their K/V across requests when it can.  Purely an
        optimization hint: results are identical with 0.

        ``priority``/``tenant``/``deadline_s``: scheduling metadata (see
        serving/scheduler.py).  With a scheduler attached, submission may
        raise :class:`SchedulerRejected` synchronously (load shed — the
        request was never queued); an expired deadline fails the future with
        :class:`DeadlineExceeded` and frees its decode slot.

        ``stream``: a :class:`~.streaming.TokenStream` to receive per-token
        events as device results resolve (EOS is not emitted) plus a terminal
        event wired through the future's done-callback — every resolution
        path (finish, deadline, failure, cancel) closes the stream.

        ``trace_id``: the request's correlation id (client ``X-Request-Id``
        or a router-assigned id); generated here when absent, stamped on the
        ``_Request``, and carried through the obs plane's trace ring and
        flight recorder (docs/OBSERVABILITY.md)."""
        trace_id = trace_id or new_trace_id()
        if self.degraded():
            # restart circuit open: fail fast (503 at the server) instead of
            # queueing work behind a device that keeps killing the loop
            remaining = max(0.1, (self._degraded_until or 0.0) - self._clock())
            raise EngineUnavailable(
                "engine degraded after repeated restarts", retry_after_s=remaining
            )
        prompt_ids = list(prompt_ids)
        if json_format and self.speculative:
            raise ValueError(
                "speculative decoding and json_format are mutually exclusive "
                "(the JSON token-FSM advances one sequential state per token); "
                "serve JSON traffic from a non-speculative model entry"
            )
        # keep room for at least one generated token (truncate BEFORE the
        # admission test: the KV demand below is computed from what will
        # actually occupy pages)
        limit = self.max_seq_len - 1
        if len(prompt_ids) > limit:
            prompt_ids = prompt_ids[-limit:]
            prefix_len = 0  # truncation drops leading tokens — prefix gone
        prefix_len = max(0, min(int(prefix_len), len(prompt_ids) - 1))
        kv_pages = 0
        if self.paged:
            # worst-case page reservation: the whole prompt plus every token
            # the request may generate, capped at the context.  Reserving up
            # front means decode can never run out of pages mid-stream — the
            # pool pressure surfaces at ADMISSION (429), not as a mid-decode
            # stall.  Prefix sharing only reduces the pages actually taken.
            demand_tokens = min(len(prompt_ids) + int(max_tokens), self.max_seq_len)
            kv_pages = -(-demand_tokens // self.kv_page_size)
        admitted = False
        if self.scheduler is not None:
            if deadline_s is None:
                deadline_s = self.scheduler.cfg.default_deadline_s
            adm = self.scheduler.try_admit(priority, deadline_s, kv_pages=kv_pages)
            if not adm.ok:
                if self.obs is not None:
                    # a shed 429 used to be uncorrelatable with the client
                    # retry that follows — the flight ring keeps the evidence,
                    # trace_id included, so a post-mortem dump matches the
                    # client-reported request id
                    self.obs.on_shed(adm.reason, priority, trace_id=trace_id)
                raise SchedulerRejected(adm.reason, adm.retry_after_s)
            if adm.clamp_max_tokens is not None:
                max_tokens = min(max_tokens, adm.clamp_max_tokens)
                if self.paged:
                    # the clamp shrinks the worst case; release the difference
                    demand_tokens = min(
                        len(prompt_ids) + int(max_tokens), self.max_seq_len
                    )
                    new_pages = -(-demand_tokens // self.kv_page_size)
                    if new_pages < kv_pages:
                        self.scheduler.release_kv(kv_pages - new_pages)
                        kv_pages = new_pages
            admitted = True
        now = self._clock()
        fut: Future = Future()
        if stream is not None:
            # attach BEFORE the queue put: if the engine resolves (or drains)
            # the future immediately, the callback still fires post-hoc
            fut.add_done_callback(stream.finish)
        if self.obs is not None:
            self.obs.on_admit(trace_id, priority, tenant, len(prompt_ids))
        self._queue.put(
            _Request(
                prompt_ids=prompt_ids,
                max_tokens=max_tokens,
                temperature=temperature,
                top_p=top_p,
                future=fut,
                submitted_at=now,
                json=json_format,
                prefix_len=prefix_len,
                priority=priority,
                tenant=tenant,
                deadline_at=(now + deadline_s) if deadline_s is not None else None,
                admitted=admitted,
                stream=stream,
                kv_pages=kv_pages,
                trace_id=trace_id,
            )
        )
        # A stop() racing (or preceding) the put above would leave the request
        # enqueued forever with no engine thread to fail it.  Re-checking after the
        # put closes the race: either the engine was still draining (it resolves the
        # future) or we drain it here — _safe_resolve makes double-resolution benign.
        # Only the thread-safe queue is touched from this (client) thread.
        if not self._running:
            self._drain_incoming(RuntimeError("generation engine stopped"))
        return fut

    async def generate(
        self,
        prompt: str | Sequence[dict],
        *,
        max_tokens: int = 1024,
        temperature: float = 0.8,
        top_p: float = 0.95,
        json_format: bool = False,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        trace_id: Optional[str] = None,
    ) -> GenerationResult:
        """Async convenience: tokenize (chat-templating message lists), run, decode."""
        import asyncio

        from .tokenizer import encode_chat_split

        if isinstance(prompt, str):
            ids, plen = self.tokenizer.encode(prompt), 0
        else:
            # everything before the final user message is the shared-prefix
            # candidate for the KV prefix cache
            ids, plen = encode_chat_split(self.tokenizer, prompt)
        fut = self.submit(
            ids,
            max_tokens=max_tokens,
            temperature=temperature,
            top_p=top_p,
            json_format=json_format,
            prefix_len=plen,
            priority=priority,
            tenant=tenant,
            deadline_s=deadline_s,
            trace_id=trace_id,
        )
        return await asyncio.wrap_future(fut)

    async def generate_stream(
        self,
        prompt: str | Sequence[dict],
        *,
        max_tokens: int = 1024,
        temperature: float = 0.8,
        top_p: float = 0.95,
        json_format: bool = False,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        trace_id: Optional[str] = None,
    ):
        """Async iterator of :class:`~.streaming.StreamChunk`: per-token
        UTF-8-safe text deltas as device results resolve, then one terminal
        chunk with the finish reason and the full :class:`GenerationResult`.

        The concatenation of every chunk's ``text`` is byte-identical to the
        non-streaming ``generate()`` result for the same request + seed —
        incomplete multi-byte fragments are held back, never replaced.

        Abandoning the iterator (``aclose``/GC on client disconnect) cancels
        the request; the engine's per-iteration reap frees its decode slot
        within one tick via the deadline epoch mechanism, so an abandoned
        generation stops burning device capacity immediately.

        ``json_format`` streams the grammar-constrained tokens as ordinary
        text deltas (each prefix is a prefix of one valid JSON document); the
        HTTP layer rejects ``stream`` + ``json_format`` instead — see
        docs/STREAMING.md."""
        import asyncio

        from .streaming import IncrementalDetokenizer, StreamChunk, TokenStream
        from .tokenizer import encode_chat_split

        if isinstance(prompt, str):
            ids, plen = self.tokenizer.encode(prompt), 0
        else:
            ids, plen = encode_chat_split(self.tokenizer, prompt)
        stream = TokenStream().bind(
            asyncio.get_running_loop(), capacity=int(max_tokens) + 2
        )
        fut = self.submit(
            ids,
            max_tokens=max_tokens,
            temperature=temperature,
            top_p=top_p,
            json_format=json_format,
            prefix_len=plen,
            priority=priority,
            tenant=tenant,
            deadline_s=deadline_s,
            stream=stream,
            trace_id=trace_id,
        )
        detok = IncrementalDetokenizer(self.tokenizer)
        idx = 0
        try:
            async for kind, payload in stream:
                if kind == "token":
                    text = detok.push(payload)
                    yield StreamChunk(index=idx, token_id=payload, text=text)
                    idx += 1
                    continue
                if isinstance(payload, BaseException):
                    raise payload
                result: GenerationResult = payload
                yield StreamChunk(
                    index=idx,
                    token_id=None,
                    text=detok.flush(),
                    done=True,
                    finish_reason="length" if result.length_limited else "stop",
                    result=result,
                )
                return
        finally:
            # consumer gone (disconnect / break / error): cancel so the
            # per-iteration reap frees the slot within one decode tick
            if not fut.done():
                fut.cancel()

    @property
    def num_active(self) -> int:
        return sum(s is not None for s in self._slots)

    def queued_depth(self) -> int:
        """Requests accepted but not yet slotted (any thread; approximate —
        the router's least-loaded dispatch reads this, and a race of one
        entry only shifts a tie-break).  With a scheduler its depth ledger is
        the single source of truth: admission charges it synchronously in
        ``submit`` (before the request even reaches the staging queue), so
        adding ``_queue.qsize()`` on top would double-count in-transit work."""
        if self.scheduler is not None:
            return self.scheduler.queue_depth
        return self._queue.qsize() + len(self._pending)

    def idle(self) -> bool:
        """No work anywhere: no live slot, no in-flight tick, no chunked
        prefill, nothing queued or mid-admission.  The graceful-drain paths
        (router ``drain()``, the server's SIGTERM drain) poll this until the
        replica has finished what it accepted.

        Takes the loop-iteration lock: between a queue pop and the wave's
        slot activation a request is in NO queue and NO slot (its prefill is
        running), and an unlocked read in that window would report an idle
        engine holding live work — the drain would then stop the engine and
        kill the request it promised to finish."""
        with self._iter_lock:
            return (
                self.num_active == 0
                and not self._inflight
                and self._chunking is None
                and self._starting_batch is None
                and self.queued_depth() == 0
                and self._queue.qsize() == 0
            )

    def holds_prefix(self, prompt_ids: Sequence[int], prefix_len: int) -> bool:
        """Does this engine's KV plane already hold a usable cached prefix of
        this prompt?  Read-only, LRU-neutral, safe from any thread — the
        router's affinity dispatch asks every replica this.  False whenever
        prefix caching is off or the layout keeps no registry worth routing
        for (the legacy LRU is engine-thread-owned; a cross-thread scan is
        best-effort and swallows the resize race)."""
        if self.prefix_cache_size <= 0 or prefix_len < self.prefix_min_tokens:
            return False
        if self.paged:
            if self._kv_pool.holds_prefix(prompt_ids, prefix_len):
                return True
            # a host/disk-tier copy is still a reason to route here: the
            # restore costs an upload, not a prefill
            return self._kv_host is not None and self._kv_host.holds(
                prompt_ids, prefix_len
            )
        n = len(prompt_ids)
        try:
            for key, ent in list(self._prefix_lru.items()):
                if ent.length < n and tuple(prompt_ids[: ent.length]) == key:
                    return True
        except RuntimeError:  # dict resized mid-scan (engine thread won)
            return False
        return False

    # ------------------------------------------------------- host KV tier
    @property
    def kv_host_tier(self):
        """The engine's host-DRAM KV tier (None when tiering is off) — the
        router's scale-down migration exports/imports through this."""
        return self._kv_host

    def _drop_restore_inflight(self, req: _Request) -> None:
        if req.restored_from_host:
            req.restored_from_host = False
            self._kv_restores_inflight = max(0, self._kv_restores_inflight - 1)

    def _fetch_pages_host(self, pages: Sequence[int]):
        """Device->host copy of whole pages (``[L, n, KH, page, D]`` x2) —
        the spill side of the tier.  Engine-thread-only (the cache is
        engine-thread-owned); called from the allocator's eviction/
        write-through paths, which run under admission, never under the
        decode hot path (dabtlint DABT104 stays at 0 findings)."""
        if not pages:
            return None
        with self._mesh_scope():
            k, v = self._gather_pages(
                self._cache, jnp.asarray(list(pages), jnp.int32)
            )
        return np.asarray(jax.device_get(k)), np.asarray(jax.device_get(v))

    def _on_kv_tier_event(
        self, event: str, key: tuple, length: int, pages: int
    ) -> None:
        """Every tier transition is a flight-recorder event, then forwards to
        the fleet prefix registry's listener (router-owned).  Fired outside
        the allocator/tier locks; thread-safe (engine thread for
        spill/restore/register, router thread when a migration target
        absorbs entries)."""
        if self.obs is not None:
            self.obs.flight.record(
                "kv_tier",
                op=event,
                prefix_tokens=int(length),
                pages=int(pages),
            )
        fn = self._prefix_listener
        if fn is not None:
            try:
                fn(event, key, length, pages)
            except Exception:
                logger.exception("fleet prefix listener failed (%s)", event)

    def set_prefix_listener(self, fn: Optional[Callable[..., None]]) -> None:
        """Subscribe the router's fleet prefix registry to this engine's
        tier-transition events (register/spill/restore/evict)."""
        self._prefix_listener = fn

    def spill_registered_to_host(self) -> int:
        """Force a host copy of every device-registry entry that lacks one —
        the scale-down migration's export step (a cheap ``has()`` sweep when
        write-through already mirrored everything, which is the default).
        Takes ``_iter_lock`` so the page gather cannot interleave with a loop
        iteration (the probe_decode discipline); resolves no futures under
        it.  Returns how many entries were newly spilled."""
        if not self.paged or self._kv_host is None:
            return 0
        n = 0
        with self._iter_lock:
            for key, ent in self._kv_pool.shared_entries():
                if self._kv_host.has(key):
                    continue
                try:
                    fetched = self._fetch_pages_host(ent.pages)
                except Exception:
                    # a dead/poisoned device mid-migration: the entry is
                    # lost (counted by the router), migration continues —
                    # charged to the same gauge as the evict/write-through
                    # spill paths so telemetry counts every failed spill
                    self._kv_pool.spill_failures += 1
                    logger.exception("migration spill fetch failed")
                    continue
                if fetched is not None and self._kv_host.put(
                    key, ent.length, *fetched
                ):
                    n += 1
        return n

    def absorb_remote_entry(self, key: tuple, length: int, k, v) -> bool:
        """Import ONE wire-shipped prefix entry (``/fleet/kv/put`` —
        serving/fleet.py) into this engine's HOST tier, never directly into
        HBM: the entry enters through the same host-tier ``put`` every spill
        uses (same ``host_put`` event for the gossip log / prefix registry /
        flight ring) and reaches device pages only through the existing
        restore-at-admission path — so restore bit-identity across a process
        boundary is the SAME tested property as the local spill/restore
        round-trip.  Geometry and dtype are validated against THIS pool
        first: a mismatched peer's bytes would reinterpret, not restore.
        Thread-safe (host-tier lock); returns whether the entry stored."""
        tier = self._kv_host
        if tier is None or not self.paged:
            return False
        key = tuple(int(t) for t in key)
        k = np.asarray(k)
        v = np.asarray(v)
        if int(length) != len(key):
            logger.warning(
                "refusing remote KV entry: length %d != key tokens %d",
                int(length), len(key),
            )
            return False
        if k.ndim != 5 or v.ndim != 5 or k.shape[3] != self.kv_page_size:
            logger.warning(
                "refusing remote KV entry: page geometry %s does not match "
                "this pool (page=%d)", tuple(k.shape), self.kv_page_size,
            )
            return False
        expected = jnp.dtype(self.kv_cache_dtype or self.cfg.dtype)
        if k.dtype != expected or v.dtype != expected:
            logger.warning(
                "refusing remote KV entry: dtype %s does not match this "
                "pool's %s", k.dtype, expected,
            )
            return False
        return tier.put(key, int(length), k, v)

    # ---------------------------------------------------------------- internal
    def _free_slots(self) -> List[int]:
        busy = {self._chunking.slot} if self._chunking is not None else set()
        return [i for i, s in enumerate(self._slots) if s is None and i not in busy]

    def _loop_iteration(self) -> bool:
        """ONE engine-loop iteration under ``_iter_lock``: reap, admit, run a
        prefill chunk (piggybacked into the decode tick when possible) and/or
        a decode tick, then drain results ``lookahead`` ticks behind.
        Returns whether any admission/chunk progress was made (the loop's
        idle predicate).  Factored out of :meth:`_loop` so deterministic
        tests can crank iterations single-threaded (tests/test_contbatch.py's
        lockstep bit-identity rig)."""
        with self._iter_lock:  # excludes probe_decode (see there)
            self._reap_dead_slots()
            admitted = self._admit()
            ticked = False
            if self._chunking is not None:
                if (
                    self._piggyback_tick is not None
                    and self.num_active > 0
                    and not self._json.any()
                    and self._chunking.step < len(self._chunking.starts) - 1
                ):
                    # continuous batching: fold this chunk into the decode
                    # tick — resident slots advance decode_steps tokens in
                    # the SAME dispatch instead of waiting a chunk out.  The
                    # final chunk always runs sequentially: its logits feed
                    # the activation (first-token sample), which is its own
                    # program.
                    self._piggyback_step()
                    ticked = True
                else:
                    if self.num_active > 0:
                        # decode waited a full dispatch on this prefill
                        # chunk — the displacement the piggybacked path
                        # exists to remove (prefill_displacement_frac)
                        self._prefill_displaced_ticks += 1
                    self._chunk_step()
                admitted = True
            if self.num_active > 0 and not ticked:
                self._issue_tick()
            # process results `lookahead` ticks behind; drain fully
            # when no slot is live (remaining in-flight ticks carry
            # final tokens)
            while self._inflight and (
                len(self._inflight) > self.lookahead
                or self.num_active == 0
            ):
                self._process_tick()
            # double-buffer next tick's sampling/block-table
            # uploads against the ticks still in flight (the
            # finishes above are what dirtied the arrays)
            self._prestage_uploads()
        return admitted

    def _loop(self):
        try:
            while self._running:
                self._beat = self._clock()
                if self._degraded_until is not None and not self._degraded_wait():
                    continue
                try:
                    admitted = self._loop_iteration()
                    # a clean iteration closes any failure streak (the restart
                    # backoff escalates over CONSECUTIVE failures only)
                    self._consecutive_failures = 0
                    if not admitted and self.num_active == 0 and not self._inflight:
                        self._sleep(self.idle_poll_s)
                except Exception as e:
                    logger.exception(
                        "engine-fatal loop error; attempting crash-only restart"
                    )
                    with self._iter_lock:
                        self._restart(e)
                    # bounded exponential backoff between restarts: a
                    # persistent device fault must not spin the loop hot
                    self._backoff_after_failure()
        finally:
            self._shutdown()

    def _degraded_wait(self) -> bool:
        """One degraded-mode loop beat.  Returns True when the cooldown has
        elapsed (half-open: restart history clears and the loop resumes —
        the next fault inside the window re-trips immediately)."""
        now = self._clock()
        if self._degraded_until is not None and now >= self._degraded_until:
            logger.warning(
                "engine circuit half-open: resuming after %.1fs degraded cooldown",
                self.degraded_cooldown_s,
            )
            # restart HISTORY is kept: a still-broken device re-trips on its
            # first post-cooldown crash (while prior restarts remain inside
            # restart_window_s) instead of burning max_restarts fresh crash/
            # rebuild cycles; a healthy resume ages the history out naturally
            self._degraded_until = None
            self._consecutive_failures = 0
            return True
        # new work fast-fails in submit(); anything already queued keeps
        # honoring deadlines/cancels while the engine cools down
        with self._iter_lock:
            self._reap_dead_slots()
        self._sleep(min(0.05, max(0.0, (self._degraded_until or now) - now)))
        return False

    def _backoff_after_failure(self) -> None:
        self._consecutive_failures += 1
        if not self._running or self.degraded():
            return  # the degraded wait (or shutdown) is the backoff
        delay = min(
            self.restart_backoff_max_s,
            self.restart_backoff_s * (2 ** (self._consecutive_failures - 1)),
        )
        if delay > 0:
            self._sleep(delay)

    def _shutdown(self):
        """End-of-loop drain, run BY the engine thread: fail live slots and
        everything queued.  Keeping this on the engine thread means stop() can
        deadline its join without racing engine-private state."""
        # however the loop exited (stop(), loop crash, failed recovery), the
        # flag must drop so submit()'s post-put re-check fails new work fast
        self._running = False
        err = RuntimeError("generation engine stopped")
        self._inflight.clear()
        for i, s in enumerate(self._slots):
            if s is not None:
                _safe_resolve(s.request.future, exc=err)
                self._slots[i] = None
                self._slot_epoch[i] += 1
            if self.paged:
                self._free_slot_pages(i)
        self._drain_queue(err)

    def _reap_dead_slots(self) -> None:
        """Free live slots whose request is dead: deadline expired or future
        cancelled by the client.  Runs at the top of every loop iteration, so
        an expired request's slot is reclaimed within ONE decode tick — the
        epoch bump drops its in-flight speculative tokens and the inactive row
        stops burning decode work (``active=False`` in the next tick; the
        stale cache row is overwritten by the next admission, the same
        discipline ``_finish`` relies on).

        QUEUED dead entries are reaped here too — every iteration, not only
        when a free slot pulls them to the fair-share head — so a queued
        request's DeadlineExceeded lands at ~its deadline even on a saturated
        engine, and dead entries stop inflating queue depth (which would shed
        admittable work with spurious queue_full 429s)."""
        now = self._clock()
        if self.scheduler is not None:
            self.scheduler.reap(now)
        elif self._pending:
            keep: "collections.deque[_Request]" = collections.deque()
            while self._pending:
                req = self._pending.popleft()
                if req.future.cancelled():
                    continue
                if req.deadline_at is not None and now >= req.deadline_at:
                    _safe_resolve(
                        req.future,
                        exc=DeadlineExceeded(
                            f"deadline expired after "
                            f"{now - req.submitted_at:.2f}s in queue"
                        ),
                    )
                    continue
                keep.append(req)
            self._pending = keep
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            req = s.request
            expired = req.deadline_at is not None and now >= req.deadline_at
            if not expired and not req.future.cancelled():
                continue
            self._slots[i] = None
            self._slot_epoch[i] += 1
            self._json[i] = False
            self._sampling_dirty = True
            self._free_slot_pages(i)
            self.reclaimed_slots += 1
            if not expired:
                # future.cancelled(): a streaming consumer disconnected (or a
                # client dropped its future) — same reap, separate counter
                self.cancelled_slots += 1
            if expired:
                _safe_resolve(
                    req.future,
                    exc=DeadlineExceeded(
                        f"deadline expired after {len(s.generated)} generated "
                        f"tokens ({now - req.submitted_at:.2f}s since submit)"
                    ),
                )
                if self.scheduler is not None:
                    self.scheduler.note_expired_running(req.priority)

    def _prefix_lookup(self, req: _Request):
        """LONGEST cached prefix this prompt starts with, or None.

        Longest-match (not exact-key) is what makes multi-turn dialogs hit:
        turn N's prompt extends turn N-1's [system, ...history] block, so the
        previous turn's registered prefix is a proper prefix of the new prompt
        even though the declared split point moved.  LRU-touches the winner.

        Paged layout: the allocator's registry answers (a
        :class:`~.kv_pool.SharedPrefix` of physical pages); legacy: the
        pinned-K/V LRU (:class:`_Prefix`).  Both carry ``.length``."""
        if self.prefix_cache_size <= 0 or req.prefix_len < self.prefix_min_tokens:
            return None
        if self.paged:
            hit = self._kv_pool.lookup(req.prompt_ids, req.prefix_len)
            hit = self._paged_usable_hit(req, hit)
            if hit is not None:
                return hit
            if self._kv_host is not None:
                # HBM missed (evicted, or a pre-restart registration): the
                # host tier may still hold the prefix — admission restores
                # it into fresh pages instead of re-prefilling.  An HBM hit
                # always wins over a host hit (no upload, no fresh pages).
                ent = self._kv_host.lookup(
                    req.prompt_ids,
                    req.prefix_len,
                    min_tokens=self.prefix_min_tokens,
                )
                if ent is not None:
                    return self._paged_usable_hit(req, _HostHit(ent))
            return None
        n = len(req.prompt_ids)
        best_key = None
        best: Optional[_Prefix] = None
        for key, ent in self._prefix_lru.items():
            if ent.length < n and (best is None or ent.length > best.length):
                if tuple(req.prompt_ids[: ent.length]) == key:
                    best, best_key = ent, key
        if best_key is not None:
            self._prefix_lru.move_to_end(best_key)
        return best

    def _paged_usable_hit(self, req: _Request, hit):
        """Reject a registry hit whose bucketed suffix prefill would have to
        slide left past the prefix boundary (prefix within one bucket of the
        context end): the slid window would re-WRITE physically shared pages,
        and a duplicate-index scatter with near-identical recomputed values is
        undefined.  The chunked path never slides into the prefix
        (remainder > chunk_size guarantees the final chunk starts past it)."""
        if hit is None:
            return None
        n_eff = len(req.prompt_ids) - hit.length
        if n_eff > self.chunk_size:
            return hit
        b = pick_bucket(n_eff, self.prefill_buckets, self.chunk_size)
        if hit.length + b > self.max_seq_len:
            return None
        return hit

    def _paged_admit_restore(self, slot: int, req: _Request, hit: _HostHit) -> bool:
        """Host-tier restore admission: allocate the request's full page
        demand, upload the spilled prefix K/V into the leading pages (async
        dispatch — the device stream orders it ahead of the suffix prefill
        that consumes those pages), and re-register the restored prefix so
        later requests share it in HBM again.  False = out of pages (the
        request stays queued, or retries as a full prefill)."""
        page = self.kv_page_size
        ent = hit.entry
        demand_tokens = min(
            len(req.prompt_ids) + req.max_tokens, self.max_seq_len
        )
        total = -(-demand_tokens // page)
        pages = self._kv_pool.alloc(total)
        if pages is None:
            return False
        t0 = self._clock()
        prefix_pages = pages[: ent.pages]
        with self._mesh_scope():
            self._cache = self._write_pages(
                self._cache,
                jnp.asarray(prefix_pages, jnp.int32),
                jnp.asarray(ent.k),
                jnp.asarray(ent.v),
            )
        # re-register: the registry increfs the restored pages, so they
        # outlive this request like any warm prefix.  Write-through skips
        # the redundant device->host copy (the host tier already has it).
        self._kv_pool.register(list(ent.key), ent.length, prefix_pages)
        self.kv_restores += 1
        self._kv_restores_inflight += 1
        # the tier counts the serve HERE (not in lookup — a queued head
        # re-runs the lookup every admission attempt) and LRU-touches
        self._kv_host.note_restored(ent.key)
        req.restored_from_host = True
        # the host-visible restore cost: tier lookup was already paid; this
        # window is host->device upload DISPATCH (the async-restore claim —
        # the device overlaps the copy with whatever is in flight)
        self._restore_s.append(self._clock() - t0)
        self._on_kv_tier_event("restore", ent.key, ent.length, ent.pages)
        self._slot_pages[slot] = pages
        self._block_tables[slot, :] = self._kv_sentinel
        self._block_tables[slot, : len(pages)] = pages
        self._bt_dirty = True
        return True

    def _paged_admit_slot(self, slot: int, req: _Request, hit) -> bool:
        """Reserve and wire pages for ``req`` in ``slot``: shared full prefix
        pages by reference (incref), the boundary page by copy-on-write clone,
        everything else fresh from the pool.  A host-tier hit routes to
        :meth:`_paged_admit_restore` instead.  False = the pool cannot place
        the request right now (it stays queued; pages free as slots finish)."""
        if isinstance(hit, _HostHit):
            return self._paged_admit_restore(slot, req, hit)
        page = self.kv_page_size
        demand_tokens = min(
            len(req.prompt_ids) + req.max_tokens, self.max_seq_len
        )
        total = -(-demand_tokens // page)
        shared: List[int] = []
        pinned: List[int] = []
        cow_src = None
        if hit is not None:
            # pin EVERY hit page (incl. the COW source) BEFORE alloc: alloc's
            # on-demand LRU eviction could otherwise evict this very entry and
            # hand its just-freed pages back as "fresh" pages of the same
            # request — aliasing prefix and suffix blocks to one physical page
            pinned = list(hit.pages)
            self._kv_pool.incref(pinned)
            shared = pinned[: hit.full_pages]
            if len(pinned) > hit.full_pages:
                cow_src = pinned[hit.full_pages]
        fresh = self._kv_pool.alloc(total - len(shared))
        if fresh is None:
            if pinned:
                self._kv_pool.decref(pinned)
            return False
        if cow_src is not None:
            # the sharer's own suffix K/V lands in the boundary page — clone
            # it (positions below the prefix length carry the owner's valid
            # prefix K/V; at/above it the clone holds garbage the sharer's
            # suffix prefill overwrites before it is ever unmasked)
            with self._mesh_scope():
                self._cache = self._copy_pages(
                    self._cache,
                    jnp.asarray([cow_src], jnp.int32),
                    jnp.asarray([fresh[0]], jnp.int32),
                )
            self._kv_pool.cow_copies += 1
            # the clone is done — the boundary page only needed the pin
            self._kv_pool.decref([cow_src])
        row = shared + fresh
        self._slot_pages[slot] = row
        self._block_tables[slot, :] = self._kv_sentinel
        self._block_tables[slot, : len(row)] = row
        self._bt_dirty = True
        return True

    def _free_slot_pages(self, slot: int) -> None:
        """Release a slot's page references (request finished / reclaimed /
        quarantined).  Registered prefix entries keep their own refs, so
        shared pages survive the owner; everything refcount-0 returns to the
        free list for the next admission."""
        if not self.paged or not self._slot_pages[slot]:
            return
        self._kv_pool.decref(self._slot_pages[slot])
        self._slot_pages[slot] = []
        self._block_tables[slot, :] = self._kv_sentinel
        self._bt_dirty = True

    def _peek_next(self, now: float) -> Optional[_Request]:
        """Head-of-queue inspection without removal.  Scheduler path: the
        weighted-fair-share winner (dead entries reaped inside).  Legacy FIFO
        path: the `_pending` head, skipping cancelled/expired entries.

        peek()/pop() resolve reaped DeadlineExceeded futures after releasing
        the SCHEDULER lock, but this caller runs under _iter_lock — so those
        done-callbacks execute under the iteration lock and fall under the
        CALLBACK CONTRACT at _iter_lock's creation site (callbacks must never
        acquire any engine's _iter_lock)."""
        if self.scheduler is not None:
            return self.scheduler.peek(now)
        while self._pending:
            req = self._pending[0]
            if req.future.cancelled():
                self._pending.popleft()
                continue
            if req.deadline_at is not None and now >= req.deadline_at:
                self._pending.popleft()
                _safe_resolve(
                    req.future,
                    exc=DeadlineExceeded(
                        f"deadline expired after {now - req.submitted_at:.2f}s in queue"
                    ),
                )
                continue
            return req
        return None

    def _take_next(self, now: float) -> Optional[_Request]:
        # same _iter_lock callback-contract note as _peek_next
        if self.scheduler is not None:
            return self.scheduler.pop(now)
        return self._pending.popleft() if self._pending else None

    def _requeue_front(self, req: _Request) -> None:
        """Put a just-popped request back at the head of its queue (admission
        could not start it this iteration: pool out of pages, or a chunked
        prefill is already in flight)."""
        if self.scheduler is not None:
            self.scheduler.enqueue(req, front=True)
        else:
            self._pending.appendleft(req)

    def _admit(self) -> bool:
        admitted = False
        # stage queued requests: into the scheduler (which orders them by
        # class/tenant fair share) or the FIFO deque so the head can be
        # inspected without losing order
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if self.scheduler is not None:
                self.scheduler.enqueue(req)
            else:
                self._pending.append(req)
        now = self._clock()
        free = self._free_slots()
        batch: List[tuple[int, _Request, Any]] = []
        while free:
            req = self._peek_next(now)
            if req is None:
                break
            hit = self._prefix_lookup(req)
            # with a cached prefix only the suffix runs through the model, so
            # the chunked path is needed only when the REMAINDER exceeds a chunk
            n_eff = len(req.prompt_ids) - (hit.length if hit else 0)
            if n_eff > self.chunk_size and (self._chunking is not None or batch):
                break  # one chunked prefill at a time; scheduling order preserved
            slot = free[0]
            if self.paged and not self._paged_admit_slot(slot, req, hit):
                if hit is not None:
                    # the pinned hit itself may be what eviction needed — drop
                    # it and retry as a full prefill (the entry becomes
                    # evictable), so a registry-heavy pool cannot wedge the
                    # queue head
                    hit = None
                    n_eff = len(req.prompt_ids)
                    if n_eff > self.chunk_size and (
                        self._chunking is not None or batch
                    ):
                        break
                    if not self._paged_admit_slot(slot, req, None):
                        break
                else:
                    break  # out of pages: the head waits for a slot to free
            taken = self._take_next(now)
            if taken is None:
                # the peeked request vanished between peek and pop — if its
                # admission already dispatched a restore, the pages free but
                # the in-flight gauge must drop too (the restored prefix
                # itself survives: it was re-registered)
                self._drop_restore_inflight(req)
                self._free_slot_pages(slot)
                break
            if taken is not req:
                # the head moved between peek and pop (a client cancelled the
                # peeked request, or a concurrent enqueue re-ordered the fair
                # share) — the POPPED request is the one that must be served;
                # dropping it would leave its future unresolved forever
                self._drop_restore_inflight(req)
                self._free_slot_pages(slot)
                req = taken
                hit = self._prefix_lookup(req)
                n_eff = len(req.prompt_ids) - (hit.length if hit else 0)
                if n_eff > self.chunk_size and (
                    self._chunking is not None or batch
                ):
                    self._requeue_front(req)
                    break
                if self.paged and not self._paged_admit_slot(slot, req, hit):
                    self._requeue_front(req)
                    break
            free.pop(0)
            self._count_prefix(req, hit)
            if n_eff > self.chunk_size:
                self._begin_chunked(slot, req, prefix=hit)
                admitted = True
            else:
                batch.append((slot, req, hit))
        if batch:
            # group the wave by seq bucket: short prompts must not pay the
            # longest prompt's O(S^2) attention; one dispatch per bucket group.
            # Prefix-hit rows prefill only their SUFFIX (bucketed by suffix
            # length) via prefill_suffix; misses take the full-prompt path.
            full_groups: Dict[int, List[tuple[int, _Request]]] = {}
            suffix_groups: Dict[int, List[tuple[int, _Request, _Prefix]]] = {}
            for slot, req, hit in batch:
                if hit is not None:
                    b = pick_bucket(
                        len(req.prompt_ids) - hit.length,
                        self.prefill_buckets,
                        self.chunk_size,
                    )
                    suffix_groups.setdefault(b, []).append((slot, req, hit))
                else:
                    b = pick_bucket(
                        len(req.prompt_ids), self.prefill_buckets, self.chunk_size
                    )
                    full_groups.setdefault(b, []).append((slot, req))
            # every not-yet-slotted request of the wave stays in
            # _starting_batch until its group succeeds — if an earlier group's
            # prefill raises, _restart salvages the rest instead of orphaning
            remaining = [pair for group in full_groups.values() for pair in group]
            remaining += [(s, r) for group in suffix_groups.values() for s, r, _ in group]
            self._starting_batch = remaining
            for group in full_groups.values():
                self._start_batch(group)
                for pair in group:
                    remaining.remove(pair)
            for sgroup in suffix_groups.values():
                self._start_suffix_batch(sgroup)
                for s, r, _ in sgroup:
                    remaining.remove((s, r))
            self._starting_batch = None
            admitted = True
        return admitted

    def _count_prefix(self, req: _Request, hit: Optional[_Prefix]) -> None:
        if self.prefix_cache_size > 0 and req.prefix_len >= self.prefix_min_tokens:
            if hit is not None:
                self.prefix_hits += 1
                if isinstance(hit, _HostHit):
                    # the warm-but-not-HBM subset: served via restore
                    self.kv_host_hits += 1
            else:
                self.prefix_misses += 1

    def warmup(
        self, seq_buckets: Optional[Sequence[int]] = None, json: bool = False
    ) -> None:
        """Deterministically compile every (batch-bucket, seq-bucket) prefill +
        insert + activation shape and the decode tick.  Admission-wave sizes are
        timing-dependent, so relying on warm *traffic* to hit every shape is
        racy — a multi-second XLA compile can land mid-measurement (or mid-SLA).
        ``json=True`` additionally builds the token FSM and compiles the
        JSON-constrained activation/tick variants.  Call before :meth:`start`:
        the zero-length insert writes touch only slot 0's cache row and set its
        length to 0."""
        if self._running:
            raise RuntimeError("warmup() must run before start() — the engine "
                               "thread owns the cache once running")
        buckets = set(
            b
            for b in (seq_buckets if seq_buckets is not None else self.prefill_buckets)
            if b <= self.chunk_size
        )
        # pick_bucket falls back to the cap when no bucket fits — that shape
        # must be warm too or an odd-length prompt compiles at serve time
        buckets.add(self.chunk_size)
        buckets = tuple(sorted(buckets))
        if json:
            self._ensure_fsm()
        with self._mesh_scope():
            for bucket in buckets:
                for bp in self._batch_buckets():
                    ids = jnp.zeros((bp, bucket), jnp.int32)
                    lengths = jnp.zeros((bp,), jnp.int32)
                    logits, ks, vs = self._prefill(self.params, ids, lengths)
                    if self.paged:
                        # sentinel slots + block tables: the compiled scatter
                        # shapes are exercised, every write drops on device
                        self._cache = self._insert(
                            self._cache,
                            ks,
                            vs,
                            lengths,
                            jnp.full((bp,), self.max_slots, jnp.int32),
                            jnp.full(
                                (bp, self._kv_blocks), self._kv_sentinel, jnp.int32
                            ),
                        )
                    else:
                        self._cache = self._insert(
                            self._cache, ks, vs, lengths, jnp.zeros((bp,), jnp.int32)
                        )
                    # the fused activation program keys on the batch bucket too
                    # — compile it here, discarding results (all rows OOB-drop)
                    self._activate_fn(
                        logits,
                        self._tokens_dev,
                        self._rng,
                        np.ones((bp,), np.float32),
                        np.ones((bp,), np.float32),
                        np.full((bp,), self.max_slots, np.int32),
                    )
                    if json:
                        self._activate_fn_json(
                            logits,
                            self._tokens_dev,
                            self._rng,
                            np.ones((bp,), np.float32),
                            np.ones((bp,), np.float32),
                            np.full((bp,), self.max_slots, np.int32),
                            fsm_states=self._fsm_states_dev,
                            jmask=np.zeros((bp,), bool),
                            init_row=self._fsm_init_row_dev,
                            next_tab=self._fsm_next_dev,
                            initial=self._fsm.initial,
                        )
            if self.chunk_size < self.max_seq_len - 1:
                # chunked prefill (prompts > chunk_size) has one fixed shape;
                # unreachable (and not worth compiling) when prompts are
                # truncated to max_seq_len - 1 <= chunk_size
                if self.paged:
                    _, self._cache = self._prefill_chunk(
                        self.params,
                        jnp.zeros((1, self.chunk_size), jnp.int32),
                        self._cache,
                        jnp.full((self._kv_blocks,), self._kv_sentinel, jnp.int32),
                        jnp.asarray(0, jnp.int32),
                        jnp.asarray(0, jnp.int32),
                        jnp.asarray(0, jnp.int32),
                    )
                else:
                    _, self._cache = self._prefill_chunk(
                        self.params,
                        jnp.zeros((1, self.chunk_size), jnp.int32),
                        self._cache,
                        jnp.asarray(0, jnp.int32),
                        jnp.asarray(0, jnp.int32),
                        jnp.asarray(0, jnp.int32),
                    )
                if self._piggyback_tick is not None:
                    # the continuous-batching program (chunk + decode scan):
                    # valid=0 drops every chunk write, all-False active
                    # freezes every decode row — warm is a pure compile
                    _, _pg_last, self._cache, self._rng = (
                        self._piggyback_tick(
                            self.params,
                            self._tokens_dev,
                            self._cache,
                            jnp.zeros((self.max_slots,), bool),
                            self._bt_dev,
                            jnp.asarray(self._temps),
                            jnp.asarray(self._top_ps),
                            self._rng,
                            jnp.zeros((1, self.chunk_size), jnp.int32),
                            jnp.asarray(0, jnp.int32),
                            jnp.asarray(0, jnp.int32),
                            jnp.asarray(0, jnp.int32),
                        )
                    )
            if self.prefix_cache_size > 0 and self.paged:
                # paged prefix path: the batched suffix prefill per (batch,
                # seq) bucket plus the COW page clone — sentinel targets, so
                # every warmup write drops
                for bucket in buckets:
                    for bp in self._batch_buckets():
                        logits, self._cache = self._prefill_suffix(
                            self.params,
                            jnp.zeros((bp, bucket), jnp.int32),
                            self._cache,
                            jnp.full(
                                (bp, self._kv_blocks), self._kv_sentinel, jnp.int32
                            ),
                            jnp.full((bp,), self.max_slots, jnp.int32),
                            jnp.zeros((bp,), jnp.int32),
                            jnp.zeros((bp,), jnp.int32),
                        )
                self._cache = self._copy_pages(
                    self._cache,
                    jnp.zeros((1,), jnp.int32),
                    jnp.full((1,), self._kv_sentinel, jnp.int32),
                )
                if self._kv_host is not None:
                    # host-tier spill/restore shapes for small page counts:
                    # a serve-time restore of a 1-2 page prefix (the common
                    # case) must not pay an XLA compile.  Gather-then-write
                    # of page 0 onto itself is an identity write — safe on
                    # the empty pre-start cache.
                    for n_warm in (1, 2, 3, 4):
                        if n_warm > self._kv_pool.n_pages:
                            break
                        idx = jnp.zeros((n_warm,), jnp.int32)
                        wk, wv = self._gather_pages(self._cache, idx)
                        self._cache = self._write_pages(
                            self._cache, idx, wk, wv
                        )
            elif self.prefix_cache_size > 0:
                # prefix-cache path: suffix prefill per (batch, seq) bucket +
                # the extract/insert copies per prefix bucket.  All warmup
                # writes land in slot 0 with length 0 — same discipline as the
                # zero-length inserts above.
                for bucket in buckets:
                    for bp in self._batch_buckets():
                        logits, self._cache = self._prefill_suffix(
                            self.params,
                            jnp.zeros((bp, bucket), jnp.int32),
                            self._cache,
                            jnp.zeros((bp,), jnp.int32),
                            jnp.zeros((bp,), jnp.int32),
                            jnp.zeros((bp,), jnp.int32),
                        )
                # every shape _prefix_bucket can produce: the prefill buckets
                # plus multiples of the largest one up to max_seq_len (each is
                # a trivial copy kernel — compiles in milliseconds)
                pbs = set(self.prefill_buckets)
                step = self.prefill_buckets[-1]
                pbs.update(
                    min(m * step, self.max_seq_len)
                    for m in range(1, -(-self.max_seq_len // step) + 1)
                )
                for pb in sorted(pbs):
                    pk, pv = self._extract_prefix(
                        self._cache, jnp.asarray(0, jnp.int32), pb
                    )
                    self._cache = self._insert_prefix(
                        self._cache, pk, pv, jnp.asarray(0, jnp.int32)
                    )
            toks, last, self._cache, self._rng = self._decode_tick(
                self.params,
                self._tokens_dev,
                self._cache,
                jnp.zeros((self.max_slots,), bool),
                self._bt_dev,
                jnp.asarray(self._temps),
                jnp.asarray(self._top_ps),
                self._rng,
            )
            if self.speculative:
                # every rung's spec tick + the per-admission history write,
                # then a timed micro-probe per rung so the controller's
                # breakeven test runs on MEASURED verify/decode cost ratios
                # instead of the conservative default
                self._history_dev = self._hist_set(
                    self._history_dev,
                    jnp.zeros((self.max_seq_len,), jnp.int32),
                    jnp.int32(0),
                )
                for rung in self._spec_ctl.rungs:
                    _, _, last2, self._history_dev, self._cache, self._rng = (
                        self._spec_ticks[rung](
                            self.params,
                            last,
                            self._history_dev,
                            self._cache,
                            self._bt_dev,
                            jnp.zeros((self.max_slots,), bool),
                            jnp.asarray(self._temps),
                            jnp.asarray(self._top_ps),
                            self._rng,
                        )
                    )
                jax.block_until_ready(last2)
                self._measure_spec_costs(iters=4)
            if json:
                toks, last, self._cache, self._rng, _ = self._decode_tick_json(
                    self.params,
                    last,
                    self._cache,
                    jnp.zeros((self.max_slots,), bool),
                    self._bt_dev,
                    jnp.asarray(self._temps),
                    jnp.asarray(self._top_ps),
                    self._rng,
                    self._fsm_states_dev,
                    jnp.zeros((self.max_slots,), bool),
                    self._fsm_next_dev,
                    self._fsm_allowed_dev,
                )
            jax.block_until_ready(last)

    def _resolve_kv_chunk(self, decode_kv_chunk: Optional[int]) -> Optional[int]:
        """Concrete decode KV chunk width, or None for the full-cache read.

        0 = auto: the largest of (512, 256, 128) that divides ``max_seq_len``
        into at least 2 chunks — below that the "chunked" read covers the whole
        cache anyway and the plain path has one fewer loop."""
        if decode_kv_chunk is None:
            return None
        if decode_kv_chunk == 0:
            for c in (512, 256, 128):
                if self.max_seq_len % c == 0 and self.max_seq_len // c >= 2:
                    return c
            return None
        c = int(decode_kv_chunk)
        if c <= 0 or self.max_seq_len % c or self.max_seq_len // c < 2:
            raise ValueError(
                f"decode_kv_chunk={decode_kv_chunk} must divide "
                f"max_seq_len={self.max_seq_len} into >= 2 chunks "
                f"(or be 0=auto / None=disabled)"
            )
        return c

    def _kv_read_frac(self) -> float:
        """Host-side mirror of the device's chunked-read window for THIS tick:
        chunks covering the longest live slot / total chunks.  An estimate (a
        burst advances positions mid-tick; in-flight speculation lags a little),
        but it tracks the device's traced ``hi`` bound to within one chunk."""
        c = self.decode_kv_chunk
        if not c:
            return 1.0
        n_chunks = self.max_seq_len // c
        mx = 0
        for s in self._slots:
            if s is not None:
                pos = len(s.request.prompt_ids) + len(s.generated)
                mx = max(mx, min(pos, self.max_seq_len - 1))
        return (mx // c + 1) / n_chunks

    def _batch_buckets(self) -> tuple:
        """Prefill batch-dim buckets: {1, 4, max_slots} — a whole admission wave
        prefills in ONE dispatch while the compiled-shape space stays 3 x
        seq-buckets (pow-of-two padding would explode it) and single-request
        admission pays no padding."""
        return tuple(sorted({1, min(4, self.max_slots), self.max_slots}))

    def _wave_block_tables(self, slots: List[int], pad: int) -> np.ndarray:
        """Block-table rows for a prefill wave ([Bp, n_blocks]); the first
        ``pad`` rows are batch-bucket padding and carry the page sentinel
        everywhere — their writes drop on device."""
        bt = np.full(
            (pad + len(slots), self._kv_blocks), self._kv_sentinel, np.int32
        )
        for j, slot in enumerate(slots):
            bt[pad + j] = self._block_tables[slot]
        return bt

    def _start_batch(self, batch: List[tuple[int, _Request]]):
        """One prefill dispatch for every request admitted this wave.

        The batch dim pads to a bucket; pad rows carry zero lengths, PRECEDE the
        real rows, and alias the first real slot — ``insert_sequences`` scans in
        row order, so the real row overwrites the pad's zero-length write."""
        reqs = [r for _, r in batch]
        slots = [s for s, _ in batch]
        B = len(batch)
        bucket = pick_bucket(
            max(len(r.prompt_ids) for r in reqs), self.prefill_buckets, self.chunk_size
        )
        Bp = pick_bucket(B, self._batch_buckets(), self.max_slots)
        pad = Bp - B
        ids = np.full((Bp, bucket), self.tokenizer.pad_id, np.int32)
        lengths = np.zeros((Bp,), np.int32)
        # pad rows: legacy aliases the first real slot (the insert scan's row
        # order makes the real row win); paged scatters with drop semantics,
        # so pads carry the max_slots / page sentinels instead
        slot_arr = np.full(
            (Bp,), self.max_slots if self.paged else slots[0], np.int32
        )
        for j, req in enumerate(reqs):
            n = len(req.prompt_ids)
            ids[pad + j, :n] = req.prompt_ids
            lengths[pad + j] = n
            slot_arr[pad + j] = slots[j]
        with self._mesh_scope():
            logits, ks, vs = self._prefill(
                self.params, jnp.asarray(ids), jnp.asarray(lengths)
            )
            if self.paged:
                self._cache = self._insert(
                    self._cache,
                    ks,
                    vs,
                    jnp.asarray(lengths),
                    jnp.asarray(slot_arr),
                    jnp.asarray(self._wave_block_tables(slots, pad)),
                )
            else:
                self._cache = self._insert(
                    self._cache, ks, vs, jnp.asarray(lengths), jnp.asarray(slot_arr)
                )
        # a miss with a declared prefix: capture its K/V for future requests
        # (pure device slice, async — admission never blocks on it)
        for slot, req in batch:
            self._maybe_register_prefix(slot, req)
        # activation consumes the FULL [Bp, V] logits so its (eager) sampling
        # and scatter shapes key on the batch bucket, not the wave size —
        # otherwise every distinct wave size would trigger fresh compiles
        self._activate_batch(slots, reqs, logits, pad=pad)

    def _start_suffix_batch(self, group: List[tuple[int, _Request, Any]]):
        """Admit a wave of prefix-cache hits: make each slot's cache row carry
        the prefix K/V — legacy copies the pinned prefix into the slot row,
        paged already wired the shared pages into the block table at admission
        — then ONE batched suffix prefill continues all rows from their
        prefix lengths; the skipped work is exactly the prefix recompute the
        reference pays every turn."""
        slots = [s for s, _, _ in group]
        reqs = [r for _, r, _ in group]
        hits = [h for _, _, h in group]
        B = len(group)
        bucket = pick_bucket(
            max(len(r.prompt_ids) - h.length for r, h in zip(reqs, hits)),
            self.prefill_buckets,
            self.chunk_size,
        )
        Bp = pick_bucket(B, self._batch_buckets(), self.max_slots)
        pad = Bp - B
        ids = np.full((Bp, bucket), self.tokenizer.pad_id, np.int32)
        starts = np.zeros((Bp,), np.int32)
        valids = np.zeros((Bp,), np.int32)
        slot_arr = np.full(
            (Bp,), self.max_slots if self.paged else slots[0], np.int32
        )
        for j, (req, hit) in enumerate(zip(reqs, hits)):
            # the bucketed write window [start, start+bucket) must not cross
            # max_seq_len — dynamic_update_slice would CLAMP the start and
            # smear the window over the prefix.  Slide the window left instead
            # (prefill_chunk's final-chunk discipline): the re-fed prefix
            # tokens recompute to identical K/V at identical positions.
            # (Paged hits never need the slide: _paged_usable_hit rejects
            # them, because a slid window would re-write SHARED pages.)
            start = min(hit.length, self.max_seq_len - bucket)
            chunk = req.prompt_ids[start : start + bucket]
            ids[pad + j, : len(chunk)] = chunk
            starts[pad + j] = start
            valids[pad + j] = len(chunk)
            slot_arr[pad + j] = slots[j]
        with self._mesh_scope():
            if self.paged:
                logits, self._cache = self._prefill_suffix(
                    self.params,
                    jnp.asarray(ids),
                    self._cache,
                    jnp.asarray(self._wave_block_tables(slots, pad)),
                    jnp.asarray(slot_arr),
                    jnp.asarray(starts),
                    jnp.asarray(valids),
                )
            else:
                for slot, hit in zip(slots, hits):
                    self._cache = self._insert_prefix(
                        self._cache, hit.pk, hit.pv, jnp.asarray(slot, jnp.int32)
                    )
                logits, self._cache = self._prefill_suffix(
                    self.params,
                    jnp.asarray(ids),
                    self._cache,
                    jnp.asarray(slot_arr),
                    jnp.asarray(starts),
                    jnp.asarray(valids),
                )
        # a hit whose DECLARED split extends past the matched prefix (multi-turn:
        # the history grew) registers the longer prefix for the next turn
        for slot, req in zip(slots, reqs):
            self._maybe_register_prefix(slot, req)
        self._activate_batch(slots, reqs, logits, pad=pad)

    def _prefix_bucket(self, prefix_len: int) -> int:
        """Device shape for a cached prefix: the smallest prefill bucket that
        fits, else the smallest MULTIPLE of the largest bucket that does (never
        the max_seq_len fallback — at 8B geometry that would pin a full-context
        ~1 GB K/V copy per entry to save a few hundred tokens of recompute).
        Capped at max_seq_len; waste is bounded by one bucket of padding."""
        for b in self.prefill_buckets:
            if prefix_len <= b:
                return b
        step = self.prefill_buckets[-1]
        return min(-(-prefix_len // step) * step, self.max_seq_len)

    def _prefix_nbytes(self, ent: _Prefix) -> int:
        try:
            return int(ent.pk.nbytes) + int(ent.pv.nbytes)
        except Exception:  # non-array stand-ins in tests
            return 0

    def _maybe_register_prefix(self, slot: int, req: _Request) -> None:
        """After a full prefill of ``slot``, make the request's declared prefix
        shareable.  Paged: register the pages covering it with the allocator
        (pure refcounting — no copy, no extra HBM beyond what the request
        already holds).  Legacy: slice the prefix K/V out of the slot row into
        the pinned LRU (post-RoPE, positions [0, P))."""
        if self.prefix_cache_size <= 0 or req.prefix_len < self.prefix_min_tokens:
            return
        if self.paged:
            nbp = -(-req.prefix_len // self.kv_page_size)
            pages = [int(p) for p in self._block_tables[slot, :nbp]]
            if any(p >= self._kv_sentinel for p in pages):
                return  # allocation didn't cover the prefix (shouldn't happen)
            self._kv_pool.register(req.prompt_ids, req.prefix_len, pages)
            return
        key = tuple(req.prompt_ids[: req.prefix_len])
        if key in self._prefix_lru:
            return
        pb = self._prefix_bucket(req.prefix_len)
        with self._mesh_scope():
            pk, pv = self._extract_prefix(self._cache, jnp.asarray(slot, jnp.int32), pb)
        ent = _Prefix(pk=pk, pv=pv, length=req.prefix_len, pb=pb)
        self._prefix_lru[key] = ent
        self._prefix_bytes += self._prefix_nbytes(ent)
        while self._prefix_lru and (
            len(self._prefix_lru) > self.prefix_cache_size
            or self._prefix_bytes > self.prefix_cache_max_bytes
        ):
            _, old = self._prefix_lru.popitem(last=False)
            self._prefix_bytes -= self._prefix_nbytes(old)

    def _begin_chunked(self, slot: int, req: _Request, prefix: Optional[_Prefix] = None):
        """Split a long prompt into full-size chunks.  The final chunk *slides left*
        to end exactly at the prompt end (re-feeding a few already-written positions
        — their K/V recompute to identical values) so no chunk ever carries pad
        tokens and no cache write can cross ``max_seq_len``.

        With a cached ``prefix``, its K/V are copied into the slot first and
        chunking covers only the remainder (starts begin at the prefix length;
        a sliding final chunk may re-feed a few prefix-covered positions —
        identical recompute, same as the no-prefix overlap)."""
        n = len(req.prompt_ids)
        base = prefix.length if prefix is not None else 0
        c = self.chunk_size
        flat = np.asarray(req.prompt_ids, np.int32)
        starts = list(range(base, n - c, c)) + [n - c]
        ids = np.stack([flat[s : s + c] for s in starts])
        if prefix is not None and not self.paged:
            # paged: the shared pages are already wired into the block table
            # (and the boundary page COW-cloned) by _paged_admit_slot
            with self._mesh_scope():
                self._cache = self._insert_prefix(
                    self._cache, prefix.pk, prefix.pv, jnp.asarray(slot, jnp.int32)
                )
        req.started_at = self._clock()
        self._chunking = _ChunkedPrefill(
            request=req, slot=slot, ids=ids, starts=starts, n=n
        )

    def _chunk_step(self):
        st = self._chunking
        assert st is not None
        j = st.step
        with self._mesh_scope():
            if self.paged:
                logits, self._cache = self._prefill_chunk(
                    self.params,
                    jnp.asarray(st.ids[j : j + 1]),
                    self._cache,
                    jnp.asarray(self._block_tables[st.slot]),
                    jnp.asarray(st.slot, jnp.int32),
                    jnp.asarray(st.starts[j], jnp.int32),
                    jnp.asarray(self.chunk_size, jnp.int32),
                )
            else:
                logits, self._cache = self._prefill_chunk(
                    self.params,
                    jnp.asarray(st.ids[j : j + 1]),
                    self._cache,
                    jnp.asarray(st.slot, jnp.int32),
                    jnp.asarray(st.starts[j], jnp.int32),
                    jnp.asarray(self.chunk_size, jnp.int32),
                )
        st.step += 1
        if st.request.future.cancelled():
            # the consumer vanished mid-prefill: abandon the remaining chunks
            self.reclaimed_slots += 1
            self.cancelled_slots += 1
            self._drop_restore_inflight(st.request)
            self._free_slot_pages(st.slot)
            self._chunking = None
            return
        dl = st.request.deadline_at
        if dl is not None and self._clock() >= dl:
            # expired mid-prefill: abandon the remaining chunks entirely
            self.reclaimed_slots += 1
            if self.scheduler is not None:
                self.scheduler.note_expired_running(st.request.priority)
            _safe_resolve(
                st.request.future,
                exc=DeadlineExceeded("deadline expired during chunked prefill"),
            )
            self._drop_restore_inflight(st.request)
            self._free_slot_pages(st.slot)
            self._chunking = None
            return
        if st.step >= len(st.starts):
            self._chunking = None
            self._maybe_register_prefix(st.slot, st.request)
            self._starting_batch = [(st.slot, st.request)]
            self._activate(st.slot, st.request, logits)
            self._starting_batch = None
            s = self._slots[st.slot]
            if s is not None:
                # service-model charge: every chunk dispatch (sequential or
                # piggybacked) was a unit of engine service this request
                # consumed before its first decode step
                s.prefill_chunks = st.step

    def _piggyback_step(self):
        """One continuous-batching dispatch: the admitting slot's next prefill
        chunk AND a fused decode tick for the resident slots, in ONE jitted
        program (:meth:`_make_piggyback_tick`).  Combines :meth:`_issue_tick`'s
        dispatch/pipeline bookkeeping with :meth:`_chunk_step`'s chunk
        bookkeeping; the gate in :meth:`_loop_iteration` guarantees this is
        never the FINAL chunk (whose logits feed the activation) and that no
        json/speculative state is live."""
        st = self._chunking
        assert st is not None and self._piggyback_tick is not None
        t0 = self._clock()
        if self._faults is not None:
            # same chaos sites as the plain tick: a raise here is engine-
            # fatal mid-piggyback (the chaos case tests/test_contbatch.py
            # pins: restart must leave the page pool clean)
            self._faults.maybe_raise("tick_raise", "device step")
            delay = self._faults.sleep_s("slow_tick")
            if delay:
                self._sleep(delay)
        self._refresh_sampling()
        self._decode_steps_effective = self.burst
        j = st.step
        with self._mesh_scope():
            toks, last, self._cache, self._rng = self._piggyback_tick(
                self.params,
                self._tokens_dev,
                self._cache,
                self._active_dev,
                self._bt_dev,
                self._temps_dev,
                self._top_ps_dev,
                self._rng,
                jnp.asarray(st.ids[j : j + 1]),
                jnp.asarray(st.slot, jnp.int32),
                jnp.asarray(st.starts[j], jnp.int32),
                jnp.asarray(self.chunk_size, jnp.int32),
            )
        try:
            toks.copy_to_host_async()
        except AttributeError:  # backend without async host copies
            pass
        self._tokens_dev = last
        self.steps += self.burst
        self._tick_issue_s += self._clock() - t0
        self._ticks_issued += 1
        self._kv_frac_sum += self._kv_read_frac()
        live = [
            (i, self._slot_epoch[i]) for i, s in enumerate(self._slots) if s is not None
        ]
        self._inflight.append(_TickRef(nxt=toks, slots=live))
        st.step += 1
        self._prefill_chunks_piggybacked += 1
        # the same mid-prefill reaping as _chunk_step (the decode side of the
        # dispatch needs none of this — its slots reap via _reap_dead_slots)
        if st.request.future.cancelled():
            self.reclaimed_slots += 1
            self.cancelled_slots += 1
            self._drop_restore_inflight(st.request)
            self._free_slot_pages(st.slot)
            self._chunking = None
            return
        dl = st.request.deadline_at
        if dl is not None and self._clock() >= dl:
            self.reclaimed_slots += 1
            if self.scheduler is not None:
                self.scheduler.note_expired_running(st.request.priority)
            _safe_resolve(
                st.request.future,
                exc=DeadlineExceeded("deadline expired during chunked prefill"),
            )
            self._drop_restore_inflight(st.request)
            self._free_slot_pages(st.slot)
            self._chunking = None

    def _activate(self, slot: int, req: _Request, logits):
        self._activate_batch([slot], [req], logits, pad=0)

    def _activate_batch(
        self, slots: List[int], reqs: List[_Request], logits, *, pad: int
    ):
        """Sample first tokens from prefill logits ([Bp, V], first ``pad`` rows
        are batch-bucket padding) and make the wave's slots live.

        Fully asynchronous: tokens stay on device (chained into the decode token
        array and, for JSON, the FSM states) via ONE fused jit call per batch
        bucket (:meth:`_make_activate`); host values arrive through the inflight
        pipeline — admission never pays a device sync.  Pad rows sample garbage
        dropped on device (out-of-bounds scatter index + ``mode="drop"``)."""
        temps = np.asarray([1.0] * pad + [r.temperature for r in reqs], np.float32)
        top_ps = np.asarray([1.0] * pad + [r.top_p for r in reqs], np.float32)
        scatter_idx = np.asarray([self.max_slots] * pad + slots, np.int32)
        with self._mesh_scope():
            if any(r.json for r in reqs):
                self._ensure_fsm()
                jmask = np.asarray([False] * pad + [r.json for r in reqs])
                first, self._tokens_dev, self._rng, self._fsm_states_dev = (
                    self._activate_fn_json(
                        logits,
                        self._tokens_dev,
                        self._rng,
                        temps,
                        top_ps,
                        scatter_idx,
                        fsm_states=self._fsm_states_dev,
                        jmask=jmask,
                        init_row=self._fsm_init_row_dev,
                        next_tab=self._fsm_next_dev,
                        initial=self._fsm.initial,
                    )
                )
            else:
                first, self._tokens_dev, self._rng = self._activate_fn(
                    logits, self._tokens_dev, self._rng, temps, top_ps, scatter_idx
                )
        ref_slots = []
        now_started = self._clock()
        for slot, req in zip(slots, reqs):
            if req.started_at is None:  # chunked prefills set it at begin
                req.started_at = now_started
            if req.restored_from_host:
                # the restore's consumer (the suffix prefill) is dispatched:
                # the in-flight gauge drops here, where admission completes
                req.restored_from_host = False
                self._kv_restores_inflight = max(0, self._kv_restores_inflight - 1)
            self._slots[slot] = _Slot(request=req)
            self._temps[slot] = req.temperature
            self._top_ps[slot] = req.top_p
            self._json[slot] = req.json
            ref_slots.append((slot, self._slot_epoch[slot]))
            if self.speculative:
                # seed the slot's device token history with the prompt — the
                # prompt IS the draft source (prompt-lookup); ~2-4 KB h2d per
                # admission, off the decode hot path
                row = np.zeros((self.max_seq_len,), np.int32)
                n = min(len(req.prompt_ids), self.max_seq_len)
                row[:n] = req.prompt_ids[:n]
                with self._mesh_scope():
                    self._history_dev = self._hist_set(
                        self._history_dev, jnp.asarray(row), jnp.int32(slot)
                    )
        self._sampling_dirty = True
        try:
            first.copy_to_host_async()
        except AttributeError:
            pass
        self._inflight.append(
            _TickRef(nxt=first, slots=ref_slots, first=True, offset=pad)
        )

    def _upload_dirty(self) -> bool:
        """Stage any dirty sampling/block-table arrays to the device; returns
        True when something was actually uploaded (the shared body of the
        issue-path :meth:`_refresh_sampling` and the overlapped
        :meth:`_prestage_uploads`)."""
        did = False
        if self._sampling_dirty:
            self._active_dev = jnp.asarray([s is not None for s in self._slots])
            self._temps_dev = jnp.asarray(self._temps)
            self._top_ps_dev = jnp.asarray(self._top_ps)
            self._json_dev = jnp.asarray(self._json)
            self._sampling_dirty = False
            did = True
        if self._bt_dirty:
            # [max_slots, n_blocks] int32 — a few KB, re-sent only when an
            # admission or free actually changed a block table
            self._bt_dev = jax.device_put(
                jnp.asarray(self._block_tables),
                _replicated(self.mesh) if self.mesh is not None else None,
            )
            self._bt_dirty = False
            did = True
        return did

    def _refresh_sampling(self):
        if self._upload_dirty():
            # paid on the issue path: the upload enqueue sat between this
            # tick's bookkeeping and its dispatch instead of overlapping the
            # previous tick's device time
            self._uploads_issue += 1

    def _prestage_uploads(self):
        """Double-buffer the host->device sampling/block-table uploads against
        the in-flight tick: called at the END of a loop iteration — after
        :meth:`_process_tick` freed finished slots (dirtying the arrays) and
        while up to ``lookahead`` ticks are still executing on device — so
        the next tick's arrays are already committed when its
        :meth:`_issue_tick` runs.  Uploads superseded by a later admission
        are re-staged on the issue path (counted there), standard
        double-buffer cost.  ``upload_overlap_frac`` in tick_stats is the
        fraction of upload cycles this path absorbed."""
        if self._inflight and (self._sampling_dirty or self._bt_dirty):
            if self._upload_dirty():
                self._uploads_prestaged += 1

    def upload_overlap_frac(self) -> float:
        """Fraction of sampling/block-table upload cycles dispatched while a
        tick was in flight (double-buffered) rather than on the issue path."""
        total = self._uploads_prestaged + self._uploads_issue
        return round(self._uploads_prestaged / total, 4) if total else 0.0

    def tick_stats(self) -> dict:
        """Aggregate per-tick wall breakdown (ms/tick).  `block` near zero means
        the lookahead pipeline fully hides device latency; `block` dominating
        means the device (or the tunnel) is the bottleneck and burst/slots are
        the knobs; `issue` dominating means dispatch enqueue is."""
        n = max(1, self._ticks_issued)
        out = {
            "ticks": self._ticks_issued,
            "issue_ms": round(self._tick_issue_s / n * 1e3, 3),
            "block_ms": round(self._tick_block_s / max(1, self._ticks_processed) * 1e3, 3),
            # average fraction of the allocated KV cache the decode attention
            # actually read (< 1 whenever live contexts are shorter than the
            # allocation and the chunked read is on; 1.0 with it disabled)
            "kv_read_frac": round(self._kv_frac_sum / n, 4)
            if self._ticks_issued
            else 1.0,
        }
        # decode-path gauges (docs/QUANT.md): which fast path is ACTUALLY
        # active — the configured fused depth vs what the last tick ran
        # (json_fsm slots downgrade to 1), the weight format's bit width,
        # and how much of the upload traffic the double-buffer absorbed
        out.update(self.decode_path_stats())
        if self.speculative:
            out.update(self.spec_stats())
        # KV memory plane gauges: pool occupancy, sharing fraction, allocator
        # eviction/COW counters (paged), or the pinned-prefix footprint (legacy)
        out["kv"] = self.kv_stats()
        out["reclaimed_slots"] = self.reclaimed_slots
        # device-slice identity + per-slice HBM ledger (docs/MULTICHIP.md)
        out["slice"] = self.slice_stats()
        # restart/quarantine/circuit counters + loop heartbeat (supervision)
        out["supervision"] = self.supervision_stats()
        out.update(self.latency_stats())
        if self.scheduler is not None:
            # queue-pressure snapshot: depth/pressure/shed/wait percentiles
            out["sched"] = self.scheduler.stats()
        return out

    def decode_path_stats(self) -> dict:
        """Decode fast-path gauges for tick_stats / /healthz / /metrics:
        ``decode_steps`` (configured fused depth), ``decode_steps_effective``
        (what the last plain tick actually ran — 1 while json_fsm slots are
        live), ``json_downgraded_ticks``, ``upload_overlap_frac`` (fraction
        of sampling/block-table upload cycles double-buffered against an
        in-flight tick), and ``weight_bits`` (16/8/4 — the weight format the
        decode dot is reading).  Same operator pattern as PR 7's
        ``kv_layout_effective``: the active configuration is a gauge, not a
        boot log line."""
        return {
            "decode_steps": self.decode_steps,
            "decode_steps_effective": self._decode_steps_effective,
            "json_downgraded_ticks": self._json_downgraded_ticks,
            "upload_overlap_frac": self.upload_overlap_frac(),
            "weight_bits": self.weight_bits,
            # continuous batching (docs/SCHEDULING.md "Continuous batching"):
            # is the piggyback program armed, how many chunks rode a decode
            # tick, and what fraction of dispatches decode still spent
            # waiting on a sequential prefill chunk — the displacement the
            # tentpole removes (0.0 with piggyback on and no json traffic)
            "prefill_piggyback": bool(self._piggyback_tick is not None),
            "prefill_chunks_piggybacked": self._prefill_chunks_piggybacked,
            "prefill_displacement_frac": round(
                self._prefill_displaced_ticks / max(1, self._ticks_issued), 4
            ),
            # fp8 in-dot attention (docs/QUANT.md): whether the decode
            # attention dots read the KV operand at fp8 storage width
            "attn_fp8": self.attn_fp8,
        }

    def slice_stats(self) -> dict:
        """Device-slice identity + HBM ledger for tick_stats / /healthz /
        /metrics (docs/MULTICHIP.md): which devices this replica's mesh
        actually spans, the slice id when the registry pinned it to one
        (None on the global-mesh path), and the device-resident byte
        footprint — weights plus the KV pool/cache allocation.  On an
        UNSLICED multi-replica fleet the weights are shared, so every
        replica's ``hbm_weight_bytes`` reports the same shared allocation;
        with slicing each replica's numbers are exclusively its own slice's
        (what makes the per-slice ledgers summable)."""
        return {
            "slice_id": self.slice_id,
            "devices": list(self.slice_devices),
            "sliced": self.slice_id is not None,
            "hbm_weight_bytes": self.hbm_weight_bytes,
            "hbm_kv_bytes": self.hbm_kv_bytes,
            "hbm_bytes": self.hbm_weight_bytes + self.hbm_kv_bytes,
        }

    def spec_stats(self) -> Optional[dict]:
        """Speculation gauges for tick_stats / healthz, or None on a
        non-speculative engine: cumulative draft/accept counters, the
        adaptive controller's state (acceptance EMA, per-arm EMAs, the tree
        shape currently issued) and whether — and WHY — speculation is off:
        ``spec_auto_disabled`` is the controller's breakeven verdict,
        ``spec_load_disabled`` the scheduler's degradation band."""
        if not self.speculative:
            return None
        out = {
            "spec_drafted": self.spec_drafted,
            "spec_accepted": self.spec_accepted,
            "spec_accept_rate": round(
                self.spec_accepted / max(1, self.spec_drafted), 4
            ),
            "spec_load_disabled": bool(
                self.scheduler is not None and self.scheduler.degraded()
            ),
            "spec_ticks": self.spec_ticks_issued,
            "spec_skipped_load": self.spec_skipped_load,
            "spec_skipped_accept": self.spec_skipped_accept,
        }
        out.update(self._spec_ctl.stats())
        return out

    def kv_stats(self) -> dict:
        """KV memory plane snapshot for tick_stats / healthz: layout, pool
        gauges (``kv_pages_used`` / ``kv_pages_free`` / ``kv_shared_page_frac``
        and the allocator's eviction/COW counters) when paged; the pinned
        prefix-LRU footprint when legacy.  Prefix hit/miss counters ride along
        in both layouts."""
        out: dict = {"kv_layout": "paged" if self.paged else "legacy"}
        # requested vs effective: a non-dividing context silently falls back
        # to the legacy plane at load — surfaced here (tick_stats + /healthz)
        # instead of only as a boot-log warning.  (Speculative engines no
        # longer fall back: the tree verify commits through the block table.)
        out["kv_layout_requested"] = self.kv_layout_requested
        out["kv_layout_effective"] = out["kv_layout"]
        if self.paged:
            out.update(self._kv_pool.stats())
            if self._kv_host is not None:
                # restore-side gauges (the tier's own spill/disk gauges ride
                # in through the allocator's stats): counts, in-flight, and
                # the host-visible restore-dispatch latency percentiles
                out["kv_restores"] = self.kv_restores
                out["kv_host_hits"] = self.kv_host_hits
                out["kv_restores_inflight"] = self._kv_restores_inflight
                # the engine thread appends concurrently; CPython's deque
                # raises RuntimeError when a copy races an append, which
                # must not fail a /metrics scrape mid-restore
                for _ in range(4):
                    try:
                        restore = list(self._restore_s)
                        break
                    except RuntimeError:
                        continue
                else:
                    restore = []
                out["kv_restore_p50_ms"] = self._pctl_ms(restore, 0.50)
                out["kv_restore_p95_ms"] = self._pctl_ms(restore, 0.95)
        else:
            out["prefix_entries"] = len(self._prefix_lru)
            out["prefix_bytes"] = self._prefix_bytes
        out["prefix_hits"] = self.prefix_hits
        out["prefix_misses"] = self.prefix_misses
        return out

    @staticmethod
    def _pctl_ms(samples, frac: float) -> float:
        vals = sorted(samples)
        if not vals:
            return 0.0
        idx = min(len(vals) - 1, max(0, round(frac * (len(vals) - 1))))
        return round(vals[idx] * 1e3, 3)

    def latency_stats(self) -> dict:
        """Perceived-latency percentiles over the recent sample windows:
        TTFT (submit -> first token on host) and inter-token latency, plus
        the disconnect counter — the streaming plane's operator dashboard
        (also exposed per-generator in /healthz).  ITL samples are host
        BATCH-arrival gaps: burst/speculative ticks deliver several tokens
        at once, so per-token cadence is roughly the gap divided by the
        tokens-per-tick."""
        ttft = list(self._ttft_s)
        itl = list(self._itl_s)
        return {
            "ttft_p50_ms": self._pctl_ms(ttft, 0.50),
            "ttft_p95_ms": self._pctl_ms(ttft, 0.95),
            "ttft_n": len(ttft),
            "itl_p50_ms": self._pctl_ms(itl, 0.50),
            "itl_p95_ms": self._pctl_ms(itl, 0.95),
            "itl_n": len(itl),
            "cancelled_slots": self.cancelled_slots,
        }

    def probe_decode(self, iters: int = 16, fill_len: Optional[int] = None) -> float:
        """Pure device decode rate: `iters` burst ticks issued back-to-back with
        device-chained state, one block at the end -> seconds per STEP (not per
        burst).  Separates the model's on-device step cost from engine/host
        overhead — the roofline denominator.  The loop-iteration lock excludes
        the engine thread for the probe's whole duration, so a request
        submitted mid-probe waits in the queue instead of racing the probe over
        the donated cache.

        ``fill_len=None`` probes with every slot inactive (cache lengths don't
        advance) — with the length-bucketed decode read that measures a
        near-empty cache, so callers wanting the cost at a *given* context fill
        pass ``fill_len``: the probe sets every free slot's cache length there
        and runs the ticks active, so the chunked attention reads the same KV
        window real traffic at that fill would.  Lengths advance by
        ``iters * burst`` and are reset to 0 afterwards; the garbage K/V the
        active probe writes sits beyond every future request's valid length
        until overwritten — the cache discipline decode already relies on.

        Waits up to 10 s for the loop to drain its speculative lookahead ticks
        (requests resolve `lookahead` ticks before the deque empties)."""
        deadline = self._clock() + 10.0
        while True:
            self._iter_lock.acquire()
            if self.num_active == 0 and not self._inflight and not self._chunking:
                break  # idle, and the loop is parked outside its iteration body
            self._iter_lock.release()
            if self._clock() >= deadline:
                raise RuntimeError("probe_decode requires an idle engine")
            self._sleep(0.01)
        try:
            return self._probe_decode_locked(iters, fill_len)
        finally:
            self._iter_lock.release()

    def _set_cache_lengths(self, values) -> None:
        lens = jnp.asarray(values, jnp.int32)
        if self._cache_shardings is not None:
            lens = jax.device_put(lens, self._cache_shardings.lengths)
        self._cache = self._cache._replace(lengths=lens)

    def _probe_decode_locked(self, iters: int, fill_len: Optional[int]) -> float:
        if fill_len is not None and self.paged:
            # give every slot a DISTINCT round-robin page chain so the probe's
            # block-table gathers stream the same page spread real traffic at
            # this fill would (sentinel rows would collapse every gather onto
            # one clamped page — cache-resident, overstating the rate).
            # Registry-shared pages hold VALID prefix K/V a live cache may
            # serve later — the probe's garbage writes must not touch them.
            avoid = self._kv_pool.shared_page_ids()
            scratch = [p for p in range(self._kv_pool.n_pages) if p not in avoid]
            if scratch:
                for b in range(self.max_slots):
                    for j in range(self._kv_blocks):
                        self._block_tables[b, j] = scratch[
                            (b * self._kv_blocks + j) % len(scratch)
                        ]
                self._bt_dirty = True
        self._refresh_sampling()
        active = self._active_dev
        if fill_len is not None:
            # keep headroom so rows stay active (unfrozen) for the whole probe:
            # the warm tick below also advances lengths by one burst, hence
            # iters + 1 — under-reserving would freeze rows mid-final-tick and
            # silently time near-idle micro-steps
            fill = max(
                0,
                min(int(fill_len), self.max_seq_len - (iters + 1) * self.burst - 2),
            )
            self._set_cache_lengths(np.full((self.max_slots,), fill, np.int32))
            active = jnp.ones((self.max_slots,), bool)
        try:
            return self._probe_decode_timed(iters, active)
        finally:
            if fill_len is not None:
                # every slot is free (probe requires an idle engine): stale
                # lengths carry no meaning, and zeroing keeps the next live
                # batch's chunked read window minimal.  In a finally so a
                # mid-probe dispatch error can't leave phantom fill lengths
                # widening every later batch's read window.
                self._set_cache_lengths(np.zeros((self.max_slots,), np.int32))
                if self.paged:
                    self._block_tables[:] = self._kv_sentinel
                    self._bt_dirty = True
                    self._refresh_sampling()

    def _probe_decode_timed(self, iters: int, active) -> float:
        import numpy as _np

        with self._mesh_scope():
            # one warm call (jit cache is hot after warmup(); cheap regardless)
            toks, last, self._cache, self._rng = self._decode_tick(
                self.params, self._tokens_dev, self._cache, active,
                self._bt_dev, self._temps_dev, self._top_ps_dev, self._rng,
            )
            self._tokens_dev = last
            _np.asarray(toks)  # fetch: the only barrier this backend honors
            # empty-pipeline fetches bound the tunnel RTT so it can be
            # subtracted from the timed chain below (block_until_ready has
            # been observed returning early on remote backends — a fetch of
            # the final chained value is the trustworthy sync).  Min of 3
            # samples: a single slow probe (GC pause, tunnel hiccup) would
            # over-subtract and overstate steady tok/s up to 2x (ADVICE r5).
            # each sample must be a FRESH device round-trip: re-fetching the
            # same jax.Array reads its cached host value (~us) and would
            # collapse rtt to ~0, disabling the subtraction entirely.  A tiny
            # elementwise op forces a new array per sample; the one-time
            # compile of that op is absorbed by the min.
            rtt = float("inf")
            for _ in range(3):
                t0 = self._clock()
                _np.asarray(self._tokens_dev + 0)
                rtt = min(rtt, self._clock() - t0)
            t0 = self._clock()
            for _ in range(iters):
                toks, last, self._cache, self._rng = self._decode_tick(
                    self.params, self._tokens_dev, self._cache, active,
                    self._bt_dev, self._temps_dev, self._top_ps_dev, self._rng,
                )
                self._tokens_dev = last
            _np.asarray(toks)
        wall = self._clock() - t0
        return max(wall - rtt, wall * 0.5) / (iters * self.burst)

    def _spec_disabled_gauge(self) -> dict:
        """The spec_disabled gauge bound into the scheduler's stats: which
        mechanism (if any) is currently holding speculation off, plus the
        tick counters behind it."""
        return {
            "load": bool(self.scheduler is not None and self.scheduler.degraded()),
            "acceptance": bool(
                self._spec_ctl is not None and self._spec_ctl.disabled
            ),
            "skipped_load_ticks": self.spec_skipped_load,
            "skipped_accept_ticks": self.spec_skipped_accept,
        }

    def probe_spec(self, iters: int = 8) -> dict:
        """Measured verify/decode tick costs per tree rung on an idle engine
        (same lock discipline as :meth:`probe_decode`): seconds per plain
        tick, seconds per speculative tick for every (width, depth) rung,
        the cost ratios, and each rung's breakeven accept rate.  Feeds the
        controller's cost table as a side effect — the bench's tick-cost
        sweep and the honest breakeven report both come from here."""
        if not self.speculative:
            raise RuntimeError("probe_spec requires a speculative engine")
        deadline = self._clock() + 10.0
        while True:
            self._iter_lock.acquire()
            if self.num_active == 0 and not self._inflight and not self._chunking:
                break
            self._iter_lock.release()
            if self._clock() >= deadline:
                raise RuntimeError("probe_spec requires an idle engine")
            self._sleep(0.01)
        try:
            return self._measure_spec_costs(iters)
        finally:
            self._iter_lock.release()

    def _measure_spec_costs(self, iters: int = 4) -> dict:
        """Time the plain tick and every rung's tree tick back-to-back with
        chained device state (all slots inactive — the verify forward's cost
        is fill-independent at a fixed allocation) and feed the measured
        cost ratios into the controller.  Called from warmup() (pre-start,
        lock-free) and probe_spec() (idle-locked)."""
        from ..ops.speculative import breakeven_accept_rate

        self._refresh_sampling()
        inactive = jnp.zeros((self.max_slots,), bool)

        def time_plain():
            t0 = self._clock()
            for _ in range(iters):
                toks, self._tokens_dev, self._cache, self._rng = self._decode_tick(
                    self.params, self._tokens_dev, self._cache, inactive,
                    self._bt_dev, self._temps_dev, self._top_ps_dev, self._rng,
                )
            np.asarray(toks)
            return (self._clock() - t0) / iters

        def time_rung(rung):
            t0 = self._clock()
            for _ in range(iters):
                toks, n_new, self._tokens_dev, self._history_dev, self._cache, \
                    self._rng = self._spec_ticks[rung](
                        self.params, self._tokens_dev, self._history_dev,
                        self._cache, self._bt_dev, inactive,
                        self._temps_dev, self._top_ps_dev, self._rng,
                    )
            np.asarray(toks)
            return (self._clock() - t0) / iters

        with self._mesh_scope():
            time_plain()  # warm (jit cache is hot after warmup; cheap anyway)
            plain_s = time_plain()
            out = {"plain_tick_s": plain_s, "rungs": {}}
            for rung in self._spec_ctl.rungs:
                time_rung(rung)  # warm
                spec_s = time_rung(rung)
                ratio = spec_s / max(plain_s, 1e-9)
                self._spec_ctl.note_cost(rung, ratio)
                # string keys ("WxK", the spec_rung_accept_emas convention):
                # the result is JSON-able like every other stats surface
                out["rungs"][f"{rung[0]}x{rung[1]}"] = {
                    "width": rung[0],
                    "depth": rung[1],
                    "tick_s": spec_s,
                    "cost_ratio": ratio,
                    "breakeven_accept_rate": breakeven_accept_rate(
                        ratio, rung[1]
                    ),
                }
        return out

    def _issue_tick(self):
        """Dispatch one decode tick without waiting for its result.  The token
        input chains device-to-device from the previous tick (the rng state
        too); the sampled ids stream back asynchronously and are consumed by
        :meth:`_process_tick`."""
        t0 = self._clock()
        if self._faults is not None:
            # deterministic chaos (serving/faults.py): a thrown device
            # dispatch (engine-fatal -> crash-only restart) or injected
            # latency (heartbeat-age evidence); inert when no injector is set
            self._faults.maybe_raise("tick_raise", "device step")
            delay = self._faults.sleep_s("slow_tick")
            if delay:
                self._sleep(delay)
        self._refresh_sampling()
        if self.speculative:
            if self.scheduler is not None and self.scheduler.degraded():
                # graceful degradation: under queue pressure the tree verify
                # forward is wasted work at low acceptance — fall back to
                # the plain tick (correctness is tick-kind-independent; only
                # the draft source quality suffers when speculation resumes)
                self.spec_skipped_load += 1
            else:
                # acceptance-EMA controller: pick the best rung of the tree
                # ladder, or None when even the narrowest tree cannot pay
                # for its verify forward at the measured acceptance (it
                # keeps probing so a workload shift can re-enable)
                rung = self._spec_ctl.rung()
                if rung is None:
                    self.spec_skipped_accept += 1
                else:
                    self._issue_spec_tick(t0, rung)
                    return
        # (a load- or acceptance-disabled speculative engine falls through to
        # the plain tick: _decode_tick is built at the same decode_steps
        # depth, so the cache/token chaining is identical either way)
        json_live = bool(self._json.any())
        issued_steps = 1 if json_live else self.burst
        if json_live and self.burst > 1:
            # fused ticks are disabled while json_fsm slots are live: the
            # whole batch rides the single-step json program this tick
            self._json_downgraded_ticks += 1
        self._decode_steps_effective = issued_steps
        with self._mesh_scope():
            if json_live:
                toks, last, self._cache, self._rng, self._fsm_states_dev = (
                    self._decode_tick_json(
                        self.params,
                        self._tokens_dev,
                        self._cache,
                        self._active_dev,
                        self._bt_dev,
                        self._temps_dev,
                        self._top_ps_dev,
                        self._rng,
                        self._fsm_states_dev,
                        self._json_dev,
                        self._fsm_next_dev,
                        self._fsm_allowed_dev,
                    )
                )
            else:
                toks, last, self._cache, self._rng = self._decode_tick(
                    self.params,
                    self._tokens_dev,
                    self._cache,
                    self._active_dev,
                    self._bt_dev,
                    self._temps_dev,
                    self._top_ps_dev,
                    self._rng,
                )
        try:
            toks.copy_to_host_async()
        except AttributeError:  # backend without async host copies
            pass
        self._tokens_dev = last
        self.steps += issued_steps
        self._tick_issue_s += self._clock() - t0
        self._ticks_issued += 1
        self._kv_frac_sum += self._kv_read_frac()
        live = [
            (i, self._slot_epoch[i]) for i, s in enumerate(self._slots) if s is not None
        ]
        self._inflight.append(_TickRef(nxt=toks, slots=live))

    def _issue_spec_tick(self, t0: float, rung: tuple):
        """Dispatch one fused tree-speculative tick at the controller's
        current (width, depth) rung (draft + verify + accept + commit on
        device, chained state — same pipelining discipline as the burst
        tick, but each of its ``decode_steps`` scanned verify steps advances
        a variable 1..depth+1 tokens/slot)."""
        with self._mesh_scope():
            toks, n_new, last, self._history_dev, self._cache, self._rng = (
                self._spec_ticks[rung](
                    self.params,
                    self._tokens_dev,
                    self._history_dev,
                    self._cache,
                    self._bt_dev,
                    self._active_dev,
                    self._temps_dev,
                    self._top_ps_dev,
                    self._rng,
                )
            )
        for arr in (toks, n_new):
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        self._tokens_dev = last
        self.steps += self.burst
        self._decode_steps_effective = self.burst
        self.spec_ticks_issued += 1
        self._tick_issue_s += self._clock() - t0
        self._ticks_issued += 1
        self._kv_frac_sum += 1.0  # the tree verify reads the full cache row
        live = [
            (i, self._slot_epoch[i]) for i, s in enumerate(self._slots) if s is not None
        ]
        self._inflight.append(
            _TickRef(nxt=toks, slots=live, n_new=n_new, spec_rung=rung)
        )

    def _process_tick(self):
        """Consume the oldest in-flight result (blocks until it arrives)."""
        try:
            self._process_tick_inner()
        finally:
            # deferred stream wakeups: one notify per touched stream per tick
            # (see TokenStream.push_token), flushed even on a mid-tick error
            # so no consumer is left waiting on already-appended events
            if self._stream_notify:
                for st in self._stream_notify:
                    st.notify_now()
                self._stream_notify.clear()

    def _process_tick_inner(self):
        ref = self._inflight.popleft()
        t0 = self._clock()
        vals = np.asarray(ref.nxt)
        block_s = self._clock() - t0
        self._tick_block_s += block_s
        self._ticks_processed += 1
        if self.obs is not None:
            # tick-duration histogram + periodic flight-ring summary — host
            # floats only, no device state (dabtlint DABT104 hot-path root)
            self.obs.on_tick(block_s, len(ref.slots))
        now = self._clock()
        if (
            self._faults is not None
            and ref.slots
            and self._faults.should_fire("nan_logits")
        ):
            # simulate what a NaN'd logits row yields downstream of on-device
            # sampling: garbage ids for ONE slot.  The id validation in
            # _consume_token quarantines that slot; batch-mates keep decoding.
            vals = np.array(vals, copy=True)
            if ref.first:
                vals[ref.offset] = -1
            else:
                vals[..., ref.slots[0][0]] = -1
        if ref.first:
            for j, (slot, epoch) in enumerate(ref.slots):
                s = self._slots[slot]
                if s is None or self._slot_epoch[slot] != epoch:
                    continue
                s.resident_steps += 1
                self._consume_token(slot, s, int(vals[ref.offset + j]), now)
            return
        if ref.n_new is not None:  # speculative tick: variable tokens/slot
            counts = np.asarray(ref.n_new)  # [N, B] — one row per verify step
            K = ref.spec_rung[1] if ref.spec_rung else self.speculative
            greedy_row_steps = 0
            tick_accepted = 0
            for step in range(counts.shape[0]):  # scanned steps, oldest first
                for slot, epoch in ref.slots:
                    s = self._slots[slot]
                    if s is None or self._slot_epoch[slot] != epoch:
                        continue  # finished by an earlier step; drafts dropped
                    n = int(counts[step, slot])
                    # a verify step advances 1..K+1 tokens in ~one (costlier)
                    # step; charging the tokens committed keeps the per-token
                    # service rate honest on speculative engines too
                    s.resident_steps += max(1, n)
                    # greedy rows proposed K drafts and n-1 were accepted
                    if s.request.temperature <= 0:
                        self.spec_drafted += K
                        self.spec_accepted += max(0, n - 1)
                        greedy_row_steps += 1
                        tick_accepted += max(0, n - 1)
                    for k in range(n):
                        if self._consume_token(slot, s, int(vals[step, k, slot]), now):
                            break  # remaining accepted tokens are post-EOS garbage
            if self._spec_ctl is not None and greedy_row_steps:
                # acceptance evidence for the adaptive controller — greedy
                # rows only (sampled rows never accept, by design), credited
                # per verify STEP to the rung that drafted this tick (the
                # rate normalizer is rows x steps x depth)
                self._spec_ctl.note_tick(
                    tick_accepted, K, greedy_row_steps, rung=ref.spec_rung
                )
                if self.obs is not None:
                    self.obs.on_spec_tick(tick_accepted, K * greedy_row_steps)
            return
        for slot, epoch in ref.slots:
            # a fused tick occupies the slot for ALL its steps even when EOS
            # lands mid-tick — charge the full tick so per-token residency
            # (the scheduler's service EMA denominator) reflects the real
            # tick-granularity occupancy
            s = self._slots[slot]
            if s is not None and self._slot_epoch[slot] == epoch:
                s.resident_steps += vals.shape[0]
        for k in range(vals.shape[0]):  # fused-tick steps, oldest first
            for slot, epoch in ref.slots:
                s = self._slots[slot]
                if s is None or self._slot_epoch[slot] != epoch:
                    continue  # finished by an earlier token; speculation dropped
                self._consume_token(slot, s, int(vals[k, slot]), now)

    def _consume_token(self, slot: int, s: _Slot, tok: int, now: float) -> bool:
        """Append one host-resident sampled id to its slot; returns True when
        the slot is no longer live (finished or quarantined).  Out-of-vocab
        ids — what a NaN'd logits row degenerates to after on-device top-k —
        are request-poison: quarantine this slot, keep the batch alive."""
        if not 0 <= tok < self.cfg.vocab_size:
            self._quarantine(
                slot,
                RequestPoisoned(
                    f"sampled id {tok} outside vocab [0, {self.cfg.vocab_size})"
                    " — NaN/corrupt logits suspected; request quarantined",
                    slot=slot,
                ),
            )
            return True
        s.generated.append(tok)
        self._note_token(s, tok, now)
        if self._should_finish(slot, tok):
            self._finish(slot)
            return True
        return False

    def _note_token(self, s: _Slot, tok: int, now: float) -> None:
        """Per-token host bookkeeping where device results land: TTFT and
        inter-token-latency samples, plus fan-out to the request's token
        stream (a deque append — the id is already host-resident from the
        inflight pipeline, so streaming adds no device sync).  EOS is not
        emitted: ``_finish`` strips it from the result text too."""
        req = s.request
        if req.first_token_at is None:
            req.first_token_at = now
            self._ttft_s.append(now - req.submitted_at)
            if self.obs is not None:
                self.obs.on_first_token(now - req.submitted_at)
        elif s.last_token_at is not None and now > s.last_token_at:
            # tokens of one tick batch share `now` — a zero "gap" between
            # burst/speculative batch-mates would collapse the percentiles to
            # 0; sampling only across batches measures the real host-arrival
            # cadence (per-token ITL ~ gap / tokens-per-tick)
            self._itl_s.append(now - s.last_token_at)
            if self.obs is not None:
                self.obs.on_token_gap(now - s.last_token_at)
        s.last_token_at = now
        if req.stream is not None and tok != self.tokenizer.eos_id:
            if req.stream.push_token(tok, notify=False):
                self._stream_notify.add(req.stream)

    def _should_finish(self, slot: int, tok: int) -> bool:
        s = self._slots[slot]
        assert s is not None
        if tok == self.tokenizer.eos_id:
            return True
        if len(s.generated) >= s.request.max_tokens:
            return True
        # cache full -> decode_step freezes the slot; finish as length-limited.
        # Speculative mode leaves N*(K+1)-1 tokens of headroom: one tick's N
        # scanned verify steps commit up to N*(K+1) accepted-path positions,
        # so live rows must always fit them (commit_tree_path docstring) —
        # those last tokens would have been length_limited a tick later
        # anyway.  (N=1 reduces to the historical K-token headroom.)
        headroom = (
            self.burst * (self.speculative + 1) - 1 if self.speculative else 0
        )
        if (
            len(s.request.prompt_ids) + len(s.generated)
            >= self.max_seq_len - headroom
        ):
            return True
        return False

    def _finish(self, slot: int):
        s = self._slots[slot]
        assert s is not None
        self._slots[slot] = None
        self._slot_epoch[slot] += 1  # invalidate this slot's in-flight ticks
        self._json[slot] = False
        self._sampling_dirty = True
        self._free_slot_pages(slot)
        req = s.request
        ids = s.generated
        hit_eos = bool(ids) and ids[-1] == self.tokenizer.eos_id
        if hit_eos:
            ids = ids[:-1]
        now = self._clock()
        try:
            if self._faults is not None:
                self._faults.maybe_raise("detok_raise", "detokenize")
            text = self.tokenizer.decode(ids)
        except Exception as e:
            # request-poison: only THIS request's result text is unrecoverable
            # — fail it and keep serving (the slot is already freed above)
            logger.warning("detokenization failed; quarantining request: %s", e)
            self.poisoned_requests += 1
            if self.obs is not None:
                self.obs.flight.record(
                    "quarantine", trace_id=req.trace_id, error=str(e)
                )
                self.obs.flight.dump("quarantine", trace_id=req.trace_id)
            _safe_resolve(req.future, exc=e)
            return
        detok_s = max(0.0, self._clock() - now)
        result = GenerationResult(
            token_ids=ids,
            text=text,
            prompt_tokens=len(req.prompt_ids),
            completion_tokens=len(ids),
            length_limited=not hit_eos,
            ttft_s=(req.first_token_at or now) - req.submitted_at,
            latency_s=now - req.submitted_at,
        )
        if self.scheduler is not None:
            # feed the estimated-wait admission model with true service time:
            # slot residency from prefill start (latency minus queue wait) —
            # first_token_at would omit the prefill, and under long-prompt
            # traffic prefill is the dominant component.  `tokens` is the
            # decode steps the slot actually sat through (fused ticks charge
            # their full N even when EOS lands mid-tick), so the scheduler
            # can model service per TOKEN and a decode_steps=N engine doesn't
            # inflate predicted queue waits by the tick-quantized lookahead
            # lag a short request pays (docs/SCHEDULING.md).  Prefill chunk
            # dispatches count too: piggybacked chunks ride decode ticks, so
            # without the charge a long-prompt request would look like pure
            # decode service and skew predicted waits / Retry-After /
            # autoscaler backlog optimistic.
            self.scheduler.note_service(
                now - (req.started_at or req.first_token_at or now),
                tokens=max(1, s.resident_steps + s.prefill_chunks),
            )
        if self.obs is not None:
            # close the request's span trace from the host timestamps the
            # tick path already stamped — deliver is the resolve below
            self.obs.on_finish(req, result, now=now + detok_s, detok_s=detok_s)
        _safe_resolve(req.future, result=result)

    def _quarantine(self, slot: int, err: BaseException) -> None:
        """Fail ONE slot's request and free the slot — the epoch bump drops
        its in-flight speculative tokens, and batch-mates keep decoding.  The
        slot's stale cache row is overwritten by the next admission (the same
        discipline ``_finish`` relies on)."""
        s = self._slots[slot]
        if s is None:
            return
        self._slots[slot] = None
        self._slot_epoch[slot] += 1
        self._json[slot] = False
        self._sampling_dirty = True
        self._free_slot_pages(slot)
        self.poisoned_requests += 1
        if self.obs is not None:
            self.obs.flight.record(
                "quarantine",
                trace_id=s.request.trace_id,
                slot=slot,
                error=str(err),
            )
            self.obs.flight.dump("quarantine", trace_id=s.request.trace_id)
        _safe_resolve(s.request.future, exc=err)

    def degraded(self) -> bool:
        """True while the restart circuit is open (submit() fast-fails)."""
        dl = self._degraded_until
        return dl is not None and self._clock() < dl

    def healthy(self) -> bool:
        """The single liveness predicate (any thread): running loop, alive
        thread (None = a single-threaded test/bench driver, not a death),
        circuit closed, fresh heartbeat.  /healthz (via supervision_stats)
        and the multi-replica router's dispatch gate both use THIS — they
        must never disagree about whether a replica is servable."""
        if not self._running or self.degraded():
            return False
        t = self._thread
        if t is not None and not t.is_alive():
            return False
        return (self._clock() - self._beat) < self.heartbeat_degraded_s

    def supervision_stats(self) -> dict:
        """Restart/quarantine/circuit counters + the loop heartbeat — the
        /healthz evidence that distinguishes a live engine from a wedged or
        degraded one (stale-but-green stats were the old failure mode)."""
        now = self._clock()
        age = now - self._beat
        degraded = self.degraded()
        # dead-thread detection: a loop thread that died without running its
        # finally (killed un-pythonically) leaves _running True forever; a
        # None thread is the single-threaded test/bench driver, not a death
        t = self._thread
        thread_alive = t is None or t.is_alive()
        return {
            "running": self._running,
            "thread_alive": thread_alive,
            "healthy": self.healthy(),
            "degraded": degraded,
            "loop_heartbeat_age_s": round(age, 3),
            "heartbeat_degraded_s": self.heartbeat_degraded_s,
            "engine_restarts": self.engine_restarts,
            "poisoned_requests": self.poisoned_requests,
            "circuit_trips": self.circuit_trips,
            "restarted_requests_resubmitted": self.restarted_resubmitted,
            "restarted_requests_failed": self.restarted_failed,
        }

    def _restart(self, err: BaseException):
        """Crash-only restart after an engine-fatal error: rebuild every piece
        of device state from scratch, salvage what is safely retryable, fail
        the rest.

        Salvage rules: queued work is untouched (it never reached the device);
        in-flight requests that have emitted NO tokens yet (mid-prefill,
        awaiting activation — including streams before their first delta) are
        re-submitted at the head of their (class, tenant) queue with their
        original futures, so the client never sees the crash; requests past
        their first token fail cleanly with the error (a non-stream replay
        would double-bill latency, a streamed one would repeat output).  Each
        request survives at most ``max_request_restarts`` restarts.  After
        ``max_restarts`` restarts inside ``restart_window_s`` the circuit
        opens: submit() fast-fails EngineUnavailable until the cooldown."""
        now = self._clock()
        self.engine_restarts += 1
        self._restart_times.append(now)
        if self.obs is not None:
            from .faults import FaultInjected

            if isinstance(err, FaultInjected):
                # the injector fire is its own flight event, distinct from the
                # restart it provoked — a chaos dump names the site directly
                self.obs.flight.record("fault_fire", site=err.site, error=str(err))
            self.obs.flight.record(
                "restart",
                error=f"{type(err).__name__}: {err}",
                engine_restarts=self.engine_restarts,
            )
        salvage: List[_Request] = []
        if self._starting_batch is not None:
            salvage.extend(req for _, req in self._starting_batch)
            self._starting_batch = None
        if self._chunking is not None:
            salvage.append(self._chunking.request)
            self._chunking = None
        self._inflight.clear()
        for i, s in enumerate(self._slots):
            if s is not None:
                if s.generated:
                    _safe_resolve(s.request.future, exc=err)
                else:
                    salvage.append(s.request)
            self._slots[i] = None
            self._slot_epoch[i] += 1
        self._json[:] = False
        self._sampling_dirty = True
        # cached prefixes were sliced out of the (possibly poisoned) cache
        # lineage — drop them with the rest of the device state
        self._prefix_lru.clear()
        self._prefix_bytes = 0
        if self.paged:
            # crash-only discipline for the page plane too: every page back on
            # the free list, every block table unallocated, the registry
            # emptied (its pages were part of the poisoned lineage).  The
            # device pool itself is rebuilt below with the rest.  The HOST
            # tier deliberately survives: its numpy copies were taken from a
            # healthy pool (write-through at registration), so warmed
            # sessions re-seed the fresh pool via restore on their next hit
            # instead of paying a cold prefill — the durability contract
            # docs/KV_PAGING.md "Tiered KV" chaos-tests.
            self._kv_pool.reset()
            self._kv_restores_inflight = 0
            self._slot_pages = [[] for _ in range(self.max_slots)]
            self._block_tables[:] = self._kv_sentinel
            self._bt_dirty = True
            if self.obs is not None and self._kv_host is not None:
                hs = self._kv_host.stats()
                self.obs.flight.record(
                    "kv_tier_survives_restart",
                    host_entries=hs["kv_host_entries"],
                    disk_entries=hs["kv_disk_entries"],
                )
        # a failure inside _activate_batch can leave a request both slotted
        # AND in _starting_batch — salvage each request once
        seen: set = set()
        requeue: List[_Request] = []
        for req in salvage:
            if id(req) in seen:
                continue
            seen.add(id(req))
            if req.future.cancelled():
                continue
            if req.restarts >= self.max_request_restarts:
                self.restarted_failed += 1
                if self.obs is not None:
                    self.obs.flight.record(
                        "restart_failed", trace_id=req.trace_id, restarts=req.restarts
                    )
                _safe_resolve(req.future, exc=err)
                continue
            req.restarts += 1
            req.started_at = None
            req.first_token_at = None
            self.restarted_resubmitted += 1
            if self.obs is not None:
                self.obs.flight.record(
                    "resubmit", trace_id=req.trace_id, restarts=req.restarts
                )
            requeue.append(req)
        # head of the queue, class/tenant tags riding on the request —
        # salvaged work must not requeue behind later arrivals.  Head inserts
        # reverse, so insert newest-submitted first: each (class, tenant)
        # queue ends up with its salvaged requests back in FIFO order.
        requeue.sort(key=lambda r: r.submitted_at, reverse=True)
        for req in requeue:
            if self.scheduler is not None:
                self.scheduler.enqueue(req, front=True)
            else:
                self._pending.appendleft(req)
        try:
            # the cache may have been donated into a failed call — rebuild it
            self._cache = self._fresh_cache()
            self._tokens_dev = self._fresh_tokens()
            self._fsm_states_dev = self._fresh_tokens()
            if self.speculative:
                self._history_dev = self._fresh_history()
            # the rng threads through jit outputs, so a failed device call may
            # have poisoned it — rebuild it like the rest of the device state,
            # with a reseed counter so back-to-back failures get distinct streams
            self._reseeds += 1
            self._rng = self._fresh_rng(self.steps + self._reseeds)
        except Exception:
            # Recovery itself failed (seen in practice: the original fault was
            # an OOM and the fresh cache can't allocate either).  Declare the
            # engine dead with an explicit diagnosis instead of letting the
            # raise escape as an anonymous loop crash — either way the loop
            # exits and _shutdown (which drops _running) fails everything
            # queued, so later submits fail fast rather than enqueue forever.
            logger.exception(
                "engine recovery failed; declaring the engine dead"
            )
            self._running = False
            if self.obs is not None:
                self.obs.flight.record("engine_dead", error=f"{type(err).__name__}: {err}")
                self.obs.flight.dump("engine_dead", error=str(err))
            return
        recent = [t for t in self._restart_times if t >= now - self.restart_window_s]
        if len(recent) >= self.max_restarts:
            self.circuit_trips += 1
            self._degraded_until = now + self.degraded_cooldown_s
            if self.obs is not None:
                self.obs.flight.record(
                    "circuit_open",
                    restarts_in_window=len(recent),
                    cooldown_s=self.degraded_cooldown_s,
                )
            logger.error(
                "engine circuit OPEN: %d restarts in %.0fs; degraded for %.1fs "
                "(submit fast-fails EngineUnavailable)",
                len(recent),
                self.restart_window_s,
                self.degraded_cooldown_s,
            )
        if self.obs is not None:
            # the post-mortem artifact: the whole recent-event ring (fault
            # fire, restart, per-request resubmits) as one JSON file — a
            # chaos failure is diagnosable without reproducing it
            self.obs.flight.dump("restart", error=str(err))


class EmbeddingEngine:
    """Batched, coalescing sentence-embedding engine over one encoder model.

    Requests from concurrent callers coalesce into one device batch (bucketed seq
    len, padded batch) — the docs/sec/chip fix for the reference's one-text-at-a-time
    loop.
    """

    def __init__(
        self,
        cfg: EncoderConfig,
        params,
        tokenizer: Tokenizer,
        *,
        max_batch: int = 64,
        seq_buckets: Sequence[int] = (32, 64, 128, 256, 512),
        normalize: bool = False,
        max_queue: int = 1024,
        mesh=None,
    ):
        self.cfg = cfg
        self.params = params
        self.tokenizer = tokenizer
        self.max_batch = max_batch
        self.seq_buckets = tuple(
            b for b in seq_buckets if b <= cfg.max_position_embeddings
        ) or (cfg.max_position_embeddings,)
        self.normalize = normalize
        self.mesh = mesh
        # bounded: an ingestion burst must shed (429 at the server) instead of
        # queueing unboundedly behind a single coalescer thread
        self.max_queue = max(1, int(max_queue))
        self.shed = 0
        self.dropped_cancelled = 0
        self._queue: "queue.Queue[tuple[List[str], Future]]" = queue.Queue(
            maxsize=self.max_queue
        )
        self._running = False
        self._thread: Optional[threading.Thread] = None

        cfg_c, norm_c = cfg, normalize

        def _encode(params, ids, mask):
            return encoder.encode(params, cfg_c, ids, mask, normalize=norm_c)

        if mesh is not None:
            # embeddings come back to host per request — replicate the output
            self._encode = jax.jit(_encode, out_shardings=_replicated(mesh))
        else:
            self._encode = jax.jit(_encode)

    def _mesh_scope(self):
        return self.mesh if self.mesh is not None else contextlib.nullcontext()

    def start(self) -> "EmbeddingEngine":
        if self._running:
            return self
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True, name="emb-engine")
        self._thread.start()
        return self

    def stop(self):
        self._running = False
        if self._thread:
            self._thread.join(timeout=10)
            self._thread = None
        err = RuntimeError("embedding engine stopped")
        while True:
            try:
                _, fut = self._queue.get_nowait()
            except queue.Empty:
                break
            _safe_resolve(fut, exc=err)

    def embed_sync(self, texts: Sequence[str]) -> List[List[float]]:
        """Blocking batched embed (used by the engine thread and CLI paths)."""
        out: List[List[float]] = []
        for i in range(0, len(texts), self.max_batch):
            out.extend(self._embed_batch(list(texts[i : i + self.max_batch])))
        return out

    async def embed(self, texts: Sequence[str]) -> List[List[float]]:
        import asyncio

        if not texts:
            return []
        fut: Future = Future()
        try:
            self._queue.put_nowait((list(texts), fut))
        except queue.Full:
            self.shed += 1
            # retry hint: one queue's worth of batches at ~the coalescer's
            # cadence; coarse but monotone in backlog size
            raise SchedulerRejected(
                "embedding queue full", retry_after_s=min(30.0, 1.0 + self.max_queue * 0.01)
            ) from None
        if not self._running:
            self.start()
        return await asyncio.wrap_future(fut)

    # ---------------------------------------------------------------- internal
    def _loop(self):
        while self._running:
            try:
                texts, fut = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            # coalesce whatever else is waiting right now; clients that
            # already cancelled are dropped HERE — before their texts pad out
            # a batched forward pass nobody will read
            jobs: List[tuple[List[str], Future]] = []
            total = 0
            if not fut.cancelled():
                jobs.append((texts, fut))
                total = len(texts)
            else:
                self.dropped_cancelled += 1
            while total < self.max_batch:
                try:
                    t2, f2 = self._queue.get_nowait()
                except queue.Empty:
                    break
                if f2.cancelled():
                    self.dropped_cancelled += 1
                    continue
                jobs.append((t2, f2))
                total += len(t2)
            if not jobs:
                continue
            flat = [t for ts, _ in jobs for t in ts]
            try:
                embs = self.embed_sync(flat)
            except Exception as e:
                for _, f in jobs:
                    _safe_resolve(f, exc=e)
                continue
            pos = 0
            for ts, f in jobs:
                _safe_resolve(f, result=embs[pos : pos + len(ts)])
                pos += len(ts)

    def _batch_buckets(self) -> List[int]:
        sizes, b = [], 1
        while b < self.max_batch:
            sizes.append(b)
            b *= 2
        sizes.append(self.max_batch)
        return sizes

    def warmup(self, seq_buckets: Optional[Sequence[int]] = None) -> None:
        """Deterministically compile every (batch-bucket, seq-bucket) encode
        shape so no XLA compile lands on the first oddly-sized live batch."""
        for bucket in seq_buckets if seq_buckets is not None else self.seq_buckets:
            for b in self._batch_buckets():
                ids = np.zeros((b, bucket), np.int32)
                mask = np.ones((b, bucket), np.int32)
                with self._mesh_scope():
                    self._encode(self.params, jnp.asarray(ids), jnp.asarray(mask))

    def _embed_batch(self, texts: List[str]) -> List[List[float]]:
        cap = self.seq_buckets[-1]
        encoded = [self.tokenizer.encode(t)[:cap] for t in texts]
        longest = max((len(e) for e in encoded), default=1)
        bucket = pick_bucket(longest, self.seq_buckets, cap)
        B = len(encoded)
        # pad the batch dim to a power-of-two bucket: every distinct live batch
        # size would otherwise compile its own encode program
        Bp = pick_bucket(B, self._batch_buckets(), self.max_batch)
        ids = np.full((Bp, bucket), self.tokenizer.pad_id, np.int32)
        mask = np.zeros((Bp, bucket), np.int32)
        mask[B:, 0] = 1  # pad rows see one pad token; all-zero masks divide by 0
        for i, e in enumerate(encoded):
            ids[i, : len(e)] = e
            mask[i, : len(e)] = 1
        with self._mesh_scope():
            embs = self._encode(self.params, jnp.asarray(ids), jnp.asarray(mask))
        return np.asarray(embs, np.float32)[:B].tolist()
