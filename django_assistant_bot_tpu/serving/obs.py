"""Serving-plane observability: request tracing, /metrics, crash flight recorder.

Until now the serving plane's only operational surfaces were point-in-time
gauges (``tick_stats()``, ``/healthz``) and free-text logs: no per-request
causality, no scrapeable time series, and no post-mortem trail when a
crash-only restart (docs/RESILIENCE.md) or a router re-route fires.  This
module is the missing layer, three pillars in one place:

- **Per-request tracing.**  Every request carries a ``trace_id`` (client
  ``X-Request-Id`` or generated at admission) on the engine's ``_Request``,
  across router re-route hops, and over the ``gpu_service:`` provider wire.
  Span timings come from host-side timestamps the tick path already stamps
  (``submitted_at`` / ``started_at`` / ``first_token_at`` / finish) — the
  recorder adds ZERO device syncs, enforced mechanically by dabtlint's
  DABT104 hot-path registry (the ``EngineObs.on_*`` entry points are roots).
  Completed traces land in a bounded ring (:meth:`EngineObs.traces`).
- **Prometheus metrics.**  Fixed-bucket :class:`Histogram` state updated from
  ``_process_tick``'s host bookkeeping (TTFT, inter-token latency, queue
  wait, tick duration, speculative accept ratio) plus the existing
  engine/scheduler/KV/router gauges, rendered as text exposition format by
  :func:`render_prometheus` — scraped by ``GET /metrics`` without holding
  any router lock across engine calls (the PR 7 ABBA family; the stats
  surfaces do their own locking).  :func:`parse_prometheus_text` is the
  small in-repo parser CI and the bench use to validate the exposition.
- **Crash flight recorder.**  A bounded ring of recent engine events
  (admissions, periodic tick summaries, quarantines, restarts, re-routes,
  fault-injector fires, drains) that the failure paths dump to a JSON file
  + log line (:meth:`FlightRecorder.dump`), so a chaos failure is
  diagnosable from the artifact alone.  ``DABT_FLIGHT_DIR`` overrides the
  dump location.

Everything is injectable-clock (dabtlint DABT105): no raw ``time.*()`` call
anywhere in this module — fake-clock tests drive spans and flight stamps
deterministically.  Format details and the metric catalog live in
docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import bisect
import collections
import json
import logging
import math
import os
import sys
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

logger = logging.getLogger(__name__)

ENV_FLIGHT_DIR = "DABT_FLIGHT_DIR"
ENV_LOG_JSON = "DABT_LOG_JSON"

# Fixed histogram bucket ladders (seconds unless noted).  Fixed buckets — not
# reservoirs — so scrapes are mergeable across time and replicas and the
# hot-path observe cost is one bisect + one increment.
TTFT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)
ITL_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
WAIT_BUCKETS = TTFT_BUCKETS
TICK_BUCKETS = ITL_BUCKETS
ACCEPT_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 1.0)


def new_trace_id() -> str:
    """16-hex-char request/trace id (collision odds are irrelevant at the
    ring-buffer horizons this plane keeps)."""
    return uuid.uuid4().hex[:16]


# --------------------------------------------------------------------- metrics
class Histogram:
    """Fixed-bucket histogram, Prometheus semantics (cumulative at render).

    Thread contract: :meth:`observe` is called from the engine thread's tick
    bookkeeping (a DABT104 hot-path root — it must never touch device state),
    :meth:`snapshot` from scrape threads; one small lock covers both.
    """

    __slots__ = ("bounds", "_counts", "_sum", "_n", "_lock")

    def __init__(self, bounds: Tuple[float, ...]):
        self.bounds = tuple(float(b) for b in bounds)
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = the +Inf bucket
        self._sum = 0.0
        self._n = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value
            self._n += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._n

    def raw_counts(self) -> Tuple[List[int], int]:
        """(per-bucket raw counts incl. the +Inf bucket, total n) — the
        windowing substrate: consumers diff two snapshots to quantile over
        only the observations BETWEEN them (serving/scheduler.py)."""
        with self._lock:
            return list(self._counts), self._n

    def quantile(self, q: float) -> float:
        """Bucket-interpolated ``q``-quantile of the observed values.

        The estimate linearly interpolates inside the bucket that contains
        the target rank; values in the ``+Inf`` bucket report the largest
        finite bound (a deliberate *under*-estimate — the admission plane
        uses this as a prediction, and an unbounded guess would shed
        everything forever).  Returns 0.0 on an empty histogram — callers
        gate on :attr:`count` to tell "cold" from "fast"."""
        counts, n = self.raw_counts()
        return quantile_from_counts(self.bounds, counts, q)

    def snapshot(self) -> Tuple[List[Tuple[float, int]], float, int]:
        """(cumulative ``le`` buckets, sum, count) — the exposition shape."""
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        out: List[Tuple[float, int]] = []
        acc = 0
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), acc + counts[-1]))
        return out, total, n


def quantile_from_counts(
    bounds: Tuple[float, ...], counts: List[int], q: float
) -> float:
    """The bucket-interpolation quantile over RAW per-bucket counts (the last
    entry being the +Inf bucket).  Shared by :meth:`Histogram.quantile` and
    the scheduler's windowed predictive-admission floor, which quantiles the
    DIFFERENCE of two count snapshots."""
    q = min(1.0, max(0.0, float(q)))
    n = sum(counts)
    if n == 0:
        return 0.0
    target = max(1, math.ceil(q * n))
    acc = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if acc + c >= target:
            if i >= len(bounds):  # +Inf bucket: report the finite ceiling
                return bounds[-1]
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i]
            frac = (target - acc) / c
            return lo + frac * (hi - lo)
        acc += c
    return bounds[-1]  # pragma: no cover - defensive


class _Exposition:
    """Accumulates metric families and renders Prometheus text format."""

    def __init__(self) -> None:
        self._families: "collections.OrderedDict[str, dict]" = collections.OrderedDict()

    @staticmethod
    def _fmt_labels(labels: Optional[Mapping[str, str]]) -> str:
        if not labels:
            return ""
        inner = ",".join(
            '%s="%s"' % (k, str(v).replace("\\", "\\\\").replace('"', '\\"'))
            for k, v in sorted(labels.items())
        )
        return "{%s}" % inner

    @staticmethod
    def _fmt_value(v: float) -> str:
        if v != v:  # NaN
            return "NaN"
        if v == float("inf"):
            return "+Inf"
        if v == float("-inf"):
            return "-Inf"
        if isinstance(v, bool):
            return "1" if v else "0"
        if isinstance(v, int) or float(v).is_integer():
            return str(int(v))
        return repr(float(v))

    def _family(self, name: str, mtype: str, help_text: str) -> dict:
        fam = self._families.get(name)
        if fam is None:
            fam = self._families[name] = {
                "type": mtype,
                "help": help_text,
                "samples": [],
            }
        return fam

    def add(
        self,
        name: str,
        mtype: str,
        help_text: str,
        value: Any,
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        if value is None:
            return
        if isinstance(value, bool):
            value = 1.0 if value else 0.0
        self._family(name, mtype, help_text)["samples"].append(
            (name, dict(labels or {}), float(value))
        )

    def add_histogram(
        self,
        name: str,
        help_text: str,
        hist: Histogram,
        labels: Optional[Mapping[str, str]] = None,
    ) -> None:
        fam = self._family(name, "histogram", help_text)
        buckets, total, n = hist.snapshot()
        base = dict(labels or {})
        for le, cum in buckets:
            lab = dict(base)
            lab["le"] = "+Inf" if le == float("inf") else self._fmt_value(le)
            fam["samples"].append((f"{name}_bucket", lab, float(cum)))
        fam["samples"].append((f"{name}_sum", base, float(total)))
        fam["samples"].append((f"{name}_count", base, float(n)))

    def render(self) -> str:
        lines: List[str] = []
        for name, fam in self._families.items():
            lines.append(f"# HELP {name} {fam['help']}")
            lines.append(f"# TYPE {name} {fam['type']}")
            for sample_name, labels, value in fam["samples"]:
                lines.append(
                    f"{sample_name}{self._fmt_labels(labels)} {self._fmt_value(value)}"
                )
        return "\n".join(lines) + "\n"


def parse_prometheus_text(text: str) -> Dict[str, dict]:
    """Small in-repo exposition parser/validator (CI + bench + tests).

    Returns ``{family: {"type": ..., "samples": [(name, labels, value)]}}``.
    Raises :class:`ValueError` on malformed input: a sample without a TYPE,
    an unparseable value, or a histogram whose cumulative buckets decrease or
    whose ``+Inf`` bucket disagrees with ``_count``.
    """
    families: Dict[str, dict] = {}
    typed: Dict[str, str] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            parts = rest.split()
            if len(parts) != 2:
                raise ValueError(f"malformed TYPE line: {raw!r}")
            typed[parts[0]] = parts[1]
            families.setdefault(parts[0], {"type": parts[1], "samples": []})
            continue
        if line.startswith("#"):
            continue
        # sample: name{labels} value
        if "{" in line:
            name, _, rest = line.partition("{")
            labels_raw, _, value_raw = rest.rpartition("}")
            labels: Dict[str, str] = {}
            if labels_raw:
                for pair in _split_labels(labels_raw):
                    k, _, v = pair.partition("=")
                    if not (v.startswith('"') and v.endswith('"')):
                        raise ValueError(f"unquoted label value: {raw!r}")
                    labels[k] = v[1:-1].replace('\\"', '"').replace("\\\\", "\\")
        else:
            name, _, value_raw = line.partition(" ")
            labels = {}
        name = name.strip()
        value_raw = value_raw.strip()
        try:
            value = float(value_raw.replace("+Inf", "inf").replace("-Inf", "-inf"))
        except ValueError:
            raise ValueError(f"unparseable sample value: {raw!r}") from None
        base = name
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix) and name[: -len(suffix)] in typed:
                base = name[: -len(suffix)]
                break
        if base not in typed:
            raise ValueError(f"sample {name!r} has no preceding TYPE line")
        families[base]["samples"].append((name, labels, value))
    _validate_histograms(families)
    return families


def _split_labels(raw: str) -> List[str]:
    """Split ``k="v",k2="v2"`` on commas outside quotes."""
    out, buf, in_q, esc = [], [], False, False
    for ch in raw:
        if esc:
            buf.append(ch)
            esc = False
            continue
        if ch == "\\":
            buf.append(ch)
            esc = True
            continue
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            out.append("".join(buf))
            buf = []
            continue
        buf.append(ch)
    if buf:
        out.append("".join(buf))
    return out


def _validate_histograms(families: Dict[str, dict]) -> None:
    for base, fam in families.items():
        if fam["type"] != "histogram":
            continue
        # group by the label set minus `le`
        series: Dict[tuple, dict] = {}
        for name, labels, value in fam["samples"]:
            key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
            s = series.setdefault(key, {"buckets": [], "count": None})
            if name.endswith("_bucket"):
                le = labels.get("le")
                if le is None:
                    raise ValueError(f"{base}: bucket sample without le label")
                s["buckets"].append((float(le.replace("+Inf", "inf")), value))
            elif name.endswith("_count"):
                s["count"] = value
        for key, s in series.items():
            buckets = sorted(s["buckets"])
            if not buckets:
                raise ValueError(f"{base}: histogram series {key} has no buckets")
            prev = -1.0
            for le, cum in buckets:
                if cum < prev:
                    raise ValueError(f"{base}: non-cumulative buckets at le={le}")
                prev = cum
            if buckets[-1][0] != float("inf"):
                raise ValueError(f"{base}: histogram missing +Inf bucket")
            if s["count"] is not None and buckets[-1][1] != s["count"]:
                raise ValueError(
                    f"{base}: +Inf bucket {buckets[-1][1]} != _count {s['count']}"
                )


# ------------------------------------------------------------ flight recorder
class FlightRecorder:
    """Bounded ring of recent serving events + the crash-dump writer.

    ``record()`` is cheap (one deque append under a small lock) and safe from
    any thread; ``dump()`` snapshots the ring and writes a JSON artifact —
    called from failure paths (restart, quarantine, drain), it must never
    crash recovery, so I/O errors log and return ``None``.

    Clock discipline (DABT105): event stamps use the injectable monotonic
    ``clock`` (comparable with every other serving timestamp); the dump
    artifact additionally carries one wall-clock stamp from the injectable
    ``walltime`` so operators can line artifacts up with external logs.
    """

    def __init__(
        self,
        capacity: int = 256,
        *,
        name: str = "engine",
        clock: Callable[[], float] = time.monotonic,
        walltime: Callable[[], float] = time.time,
        dump_dir: Optional[str] = None,
    ):
        self.name = name
        self._clock = clock
        self._walltime = walltime
        self._dump_dir = dump_dir
        self._lock = threading.Lock()
        self._events: "collections.deque[dict]" = collections.deque(
            maxlen=max(16, int(capacity))
        )
        self._seq = 0
        self.dumps = 0

    def record(self, event: str, **fields: Any) -> None:
        entry = {"t_mono_s": round(self._clock(), 4), "event": event}
        entry.update(fields)
        with self._lock:
            self._seq += 1
            entry["seq"] = self._seq
            self._events.append(entry)

    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def dump(self, reason: str, **context: Any) -> Optional[str]:
        """Write the ring to ``<dir>/flight-<name>-<pid>-<n>.json``; returns
        the path (None on failure — dumping must never break recovery)."""
        with self._lock:
            events = list(self._events)
            self.dumps += 1
            n = self.dumps
        payload = {
            "reason": reason,
            "recorder": self.name,
            "dumped_at_unix": round(self._walltime(), 3),
            "dumped_at_mono_s": round(self._clock(), 4),
            **context,
            "events": events,
        }
        directory = (
            os.environ.get(ENV_FLIGHT_DIR, "").strip()
            or self._dump_dir
            or os.path.join(tempfile.gettempdir(), "dabt-flight")
        )
        safe = "".join(c if (c.isalnum() or c in "._-") else "_" for c in self.name)
        path = os.path.join(directory, f"flight-{safe}-{os.getpid()}-{n:03d}.json")
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, indent=1, default=str)
            os.replace(tmp, path)
        except OSError as e:
            logger.warning("flight recorder dump failed (%s): %s", reason, e)
            return None
        logger.error(
            "flight recorder dumped: reason=%s recorder=%s events=%d -> %s",
            reason,
            self.name,
            len(events),
            path,
        )
        return path


# ----------------------------------------------------------------- engine obs
class EngineObs:
    """Per-engine observability: span traces, metric histograms, flight ring.

    The ``on_*`` methods are the hot-path entry points (registered in
    dabtlint's DABT104 registry): pure host-side bookkeeping over values
    ``_process_tick`` already holds — a device sync or raw ``time.*()`` call
    anywhere under them is a lint failure, not a code-review hope.
    """

    def __init__(
        self,
        name: str = "engine",
        *,
        clock: Callable[[], float] = time.monotonic,
        trace_capacity: int = 256,
        flight_capacity: int = 256,
        tick_summary_every: int = 64,
        dump_dir: Optional[str] = None,
    ):
        self.name = name
        self._clock = clock
        self.ttft_s = Histogram(TTFT_BUCKETS)
        self.itl_s = Histogram(ITL_BUCKETS)
        self.queue_wait_s = Histogram(WAIT_BUCKETS)
        self.tick_s = Histogram(TICK_BUCKETS)
        self.accept_ratio = Histogram(ACCEPT_BUCKETS)
        self.flight = FlightRecorder(
            flight_capacity, name=name, clock=clock, dump_dir=dump_dir
        )
        self._lock = threading.Lock()
        self._traces: "collections.deque[dict]" = collections.deque(
            maxlen=max(16, int(trace_capacity))
        )
        self.traces_total = 0
        self._tick_summary_every = max(1, int(tick_summary_every))
        self._ticks_seen = 0

    # ---- hot path (DABT104 roots; called from _process_tick bookkeeping) ----
    def on_tick(self, block_s: float, active: int) -> None:
        """One processed tick: duration histogram + a periodic flight-ring
        summary (every Nth tick, so admissions/faults aren't drowned)."""
        self.tick_s.observe(block_s)
        self._ticks_seen += 1
        if self._ticks_seen % self._tick_summary_every == 0:
            self.flight.record(
                "tick_summary",
                ticks=self._ticks_seen,
                active=active,
                block_ms=round(block_s * 1e3, 3),
            )

    def on_spec_tick(self, accepted: int, drafted: int) -> None:
        if drafted > 0:
            self.accept_ratio.observe(accepted / drafted)

    def on_first_token(self, ttft_s: float) -> None:
        self.ttft_s.observe(ttft_s)

    def on_token_gap(self, gap_s: float) -> None:
        self.itl_s.observe(gap_s)

    # ---- request lifecycle (off the per-token path) -------------------------
    def on_admit(self, trace_id: str, priority: str, tenant: str, prompt_tokens: int) -> None:
        self.flight.record(
            "admit",
            trace_id=trace_id,
            priority=priority,
            tenant=tenant,
            prompt_tokens=prompt_tokens,
        )

    def on_shed(self, reason: str, priority: str, trace_id: str = "") -> None:
        self.flight.record(
            "shed", trace_id=trace_id, reason=reason, priority=priority
        )

    def on_finish(self, req: Any, result: Any, *, now: float, detok_s: float) -> None:
        """Close a request's trace from the host timestamps the tick path
        already stamped; observes queue-wait and appends to the trace ring."""
        t0 = req.submitted_at
        started = req.started_at if req.started_at is not None else t0
        first = req.first_token_at if req.first_token_at is not None else now
        queue_wait = max(0.0, started - t0)
        self.queue_wait_s.observe(queue_wait)
        spans = [
            {"name": "admit", "t_s": 0.0},
            {"name": "queue_wait", "t_s": 0.0, "dur_s": round(queue_wait, 6)},
            {
                "name": "prefill",
                "t_s": round(started - t0, 6),
                "dur_s": round(max(0.0, first - started), 6),
            },
            {
                "name": "decode",
                "t_s": round(first - t0, 6),
                "dur_s": round(max(0.0, now - first - detok_s), 6),
                "tokens": result.completion_tokens,
            },
            {"name": "detok", "t_s": round(now - t0 - detok_s, 6), "dur_s": round(detok_s, 6)},
            {"name": "deliver", "t_s": round(now - t0, 6)},
        ]
        trace = {
            "trace_id": req.trace_id,
            "engine": self.name,
            "priority": req.priority,
            "tenant": req.tenant,
            "prompt_tokens": result.prompt_tokens,
            "completion_tokens": result.completion_tokens,
            "restarts": req.restarts,
            # submission stamp in the engine's monotonic clock domain: only
            # DIFFERENCES are meaningful, which is exactly what the workload
            # trace export needs (relative arrival offsets — workload/capture.py)
            "t_submit_s": round(t0, 6),
            "total_s": round(now - t0, 6),
            "spans": spans,
        }
        with self._lock:
            self._traces.append(trace)
            self.traces_total += 1
        self.flight.record(
            "finish",
            trace_id=req.trace_id,
            tokens=result.completion_tokens,
            total_s=round(now - t0, 4),
        )

    def traces(self) -> List[dict]:
        with self._lock:
            return list(self._traces)

    def trace(self, trace_id: str) -> Optional[dict]:
        with self._lock:
            for t in reversed(self._traces):
                if t["trace_id"] == trace_id:
                    return t
        return None


# ------------------------------------------------------------------ /metrics
# Task-plane stats provider (tasks/queue.py Worker.register_metrics): the
# queue/bot/delivery plane lives in worker processes without engines, so it
# publishes through a module-level hook instead of the engine registry.  The
# provider is a plain callable returning the queue_stats() shape; a failing
# provider must never break a scrape.
_task_plane_provider: Optional[Callable[[], Dict[str, Any]]] = None


def set_task_plane_provider(fn: Optional[Callable[[], Dict[str, Any]]]) -> None:
    global _task_plane_provider
    _task_plane_provider = fn


def _render_task_plane(x: "_Exposition") -> None:
    prov = _task_plane_provider
    if prov is None:
        return
    try:
        q = prov() or {}
    except Exception:
        logger.warning("task-plane stats provider failed", exc_info=True)
        return
    for qname, qs in sorted((q.get("queues") or {}).items()):
        lab = {"queue": qname}
        x.add("dabt_queue_depth", "gauge", "pending tasks (due + scheduled)", qs.get("pending"), lab)
        x.add("dabt_queue_running", "gauge", "leased (executing) tasks", qs.get("running"), lab)
        x.add("dabt_queue_oldest_pending_age_seconds", "gauge", "age of the oldest pending task", qs.get("oldest_pending_age_s"), lab)
        x.add("dabt_queue_dead", "gauge", "dead-lettered tasks", qs.get("dead"), lab)
    x.add("dabt_queue_dlq_size", "gauge", "dead-letter queue size across queues", q.get("dlq_size"))
    w = q.get("worker") or {}
    x.add("dabt_queue_claims_total", "counter", "task claims by this worker", w.get("claims"))
    x.add("dabt_queue_executed_total", "counter", "task executions started", w.get("executed"))
    x.add("dabt_queue_done_total", "counter", "tasks completed", w.get("done"))
    x.add("dabt_queue_retries_total", "counter", "retries scheduled (backoff or RetryLater)", w.get("retries"))
    x.add("dabt_queue_dead_letters_total", "counter", "tasks dead-lettered by this worker", w.get("dead_lettered"))
    x.add("dabt_queue_reclaimed_leases_total", "counter", "expired leases reclaimed to pending", w.get("reclaimed_leases"))
    x.add("dabt_queue_heartbeats_total", "counter", "lease heartbeat renewals", w.get("heartbeats"))
    x.add("dabt_queue_leases_lost_total", "counter", "executions that lost their lease", w.get("leases_lost"))
    x.add("dabt_queue_completions_discarded_total", "counter", "late completions discarded after a lease loss", w.get("completions_discarded"))
    d = q.get("delivery") or {}
    x.add("dabt_queue_delivery_deduped_total", "counter", "answer parts skipped by the delivery ledger", d.get("deduped_parts"))
    x.add("dabt_queue_delivery_uncertain_total", "counter", "parts skipped after a mid-POST worker death", d.get("uncertain_parts_skipped"))
    x.add("dabt_queue_turn_replays_skipped_total", "counter", "fully-delivered turns skipped on re-execution", d.get("turn_replays_skipped"))
    x.add("dabt_queue_inbound_deduped_total", "counter", "duplicate platform update_ids not re-enqueued", d.get("inbound_updates_deduped"))


# RAG-plane stats provider (rag/index_registry.rag_plane_stats): same hook
# discipline as the task plane — the vector indexes live in whatever process
# built them (API server or ingestion worker), not in the engine registry.
# When no provider is set, fall back to the registry module *if it is already
# imported* — serve-only processes that never touched the rag plane pay
# nothing on a scrape.
_rag_plane_provider: Optional[Callable[[], Dict[str, Any]]] = None


def set_rag_plane_provider(fn: Optional[Callable[[], Dict[str, Any]]]) -> None:
    global _rag_plane_provider
    _rag_plane_provider = fn


def rag_plane_snapshot() -> Dict[str, Any]:
    """Provider output (or the lazily-discovered registry's), never raising —
    shared by /metrics rendering and the /healthz ``rag`` block."""
    prov = _rag_plane_provider
    if prov is None:
        mod = sys.modules.get("django_assistant_bot_tpu.rag.index_registry")
        prov = getattr(mod, "rag_plane_stats", None)
    if prov is None:
        return {}
    try:
        return prov() or {}
    except Exception:
        logger.warning("rag-plane stats provider failed", exc_info=True)
        return {}


def _render_rag_plane(x: "_Exposition") -> None:
    snap = rag_plane_snapshot()
    for name, st in sorted((snap.get("indexes") or {}).items()):
        lab = {"index": name}
        x.add("dabt_rag_index_rows", "gauge", "live vectors in this index", st.get("rows"), lab)
        if st.get("kind") != "ivfpq":
            continue
        x.add("dabt_ann_trained", "gauge", "IVF-PQ structure trained (0=exact fallback)", 1 if st.get("trained") else 0, lab)
        x.add("dabt_ann_exact_fallback", "gauge", "searches currently served by the exact tier", 1 if st.get("exact_fallback") else 0, lab)
        x.add("dabt_ann_nlist", "gauge", "IVF coarse lists", st.get("nlist"), lab)
        x.add("dabt_ann_nprobe", "gauge", "default lists probed per query", st.get("nprobe"), lab)
        x.add("dabt_ann_codes_bytes", "gauge", "device bytes held by PQ code blocks", st.get("codes_bytes"), lab)
        x.add("dabt_ann_codes_bytes_per_vector", "gauge", "PQ code bytes per stored vector", st.get("codes_bytes_per_vector"), lab)
        x.add("dabt_ann_rerank_depth", "gauge", "exact-rerank shortlist depth", st.get("rerank_depth"), lab)
        x.add("dabt_ann_tombstones", "gauge", "removed-but-uncompacted slots", st.get("tombstones"), lab)
        x.add("dabt_ann_pending_appends", "gauge", "rows appended since the last train/compact", st.get("pending_appends"), lab)
        x.add("dabt_ann_drift_frac", "gauge", "fraction of sampled rows nearer a foreign centroid", st.get("drift_frac"), lab)
        x.add("dabt_ann_retrain_advised", "gauge", "drift gauge past the advisory threshold", 1 if st.get("retrain_advised") else 0, lab)
        x.add("dabt_ann_searches_total", "counter", "batched searches served", st.get("searches"), lab)
        x.add("dabt_ann_compactions_total", "counter", "tombstone compactions", st.get("compactions"), lab)
        x.add("dabt_ann_retrains_total", "counter", "full retrains", st.get("retrains"), lab)
        lr = st.get("last_recall") or {}
        if lr.get("recall_at_k") is not None:
            x.add("dabt_ann_last_recall", "gauge", "recall@k from the last probe_recall()", lr.get("recall_at_k"), lab)
        dur = st.get("durability")
        if dur:
            # WAL+snapshot plane (storage/durable.py, docs/DURABILITY.md):
            # wal_records is the writer's sequence high-water mark; snapshot
            # age only renders once a snapshot exists (None until then)
            x.add("dabt_ann_wal_records", "gauge", "WAL sequence high-water mark", dur.get("wal_records"), lab)
            x.add("dabt_ann_wal_bytes", "gauge", "bytes across live WAL segments", dur.get("wal_bytes"), lab)
            x.add("dabt_ann_wal_segments", "gauge", "live WAL segment files", dur.get("wal_segments"), lab)
            if dur.get("snapshot_age_s") is not None:
                x.add("dabt_ann_snapshot_age_s", "gauge", "seconds since the last committed snapshot", dur.get("snapshot_age_s"), lab)
            x.add("dabt_ann_snapshot_count", "gauge", "committed snapshots on disk", dur.get("snapshot_count"), lab)
            x.add("dabt_ann_writable", "gauge", "this process owns the WAL flock (0=read-only recovery)", 1 if dur.get("writable") else 0, lab)
            x.add("dabt_ann_recovery_replayed_records", "gauge", "WAL records replayed at last startup recovery", dur.get("replayed_records"), lab)
            x.add("dabt_ann_recovery_s", "gauge", "wall seconds spent in last startup recovery", dur.get("recovery_s"), lab)
            x.add("dabt_ann_snapshot_fallbacks_total", "counter", "corrupt snapshots skipped for an older valid one", dur.get("snapshot_fallbacks"), lab)
            x.add("dabt_ann_wal_torn_tail_truncations_total", "counter", "torn WAL tails healed at open", dur.get("torn_tail_truncations"), lab)
            x.add("dabt_ann_ledger_entries", "gauge", "idempotency-ledger keys tracked", dur.get("ledger_entries"), lab)
            x.add("dabt_ann_ledger_dedup_hits_total", "counter", "ingests no-opped by the idempotency ledger", dur.get("ledger_dedup_hits"), lab)


def _engine_rows(registry: Any) -> List[Tuple[str, str, Any, Optional[Any]]]:
    """(model, replica, engine, router-or-None) rows for every generator.

    Routers expand into their replicas; the router object itself contributes
    fleet-level samples once.  No lock is taken here — every stats surface
    the renderer touches does its own (fine-grained) locking, so a scrape
    can never hold one component's lock across another's call (the PR 7
    ABBA family this plane is witness-tested against).
    """
    rows: List[Tuple[str, str, Any, Optional[Any]]] = []
    for model, eng in sorted(getattr(registry, "generators", {}).items()):
        reps = getattr(eng, "replicas", None)
        if reps is not None:  # EngineRouter
            for rep in reps:
                rows.append((model, rep.name, rep.engine, eng))
        else:
            rows.append((model, getattr(eng, "name", "0"), eng, None))
    return rows


def render_prometheus(registry: Any) -> str:
    """Render one scrape of everything the registry serves.

    Unifies the existing gauges (engine supervision, scheduler, KV plane,
    speculation, router) with the obs histograms.  Pure read path: safe to
    call from the HTTP event loop while replicas are dead, draining, or
    mid-restart (the scrape-under-duress regression net in tests/test_obs.py).
    """
    x = _Exposition()
    routers_done: set = set()
    for model, replica, eng, router in _engine_rows(registry):
        lab = {"model": model, "replica": replica}
        sup = eng.supervision_stats()
        x.add("dabt_engine_steps_total", "counter", "device decode steps issued", eng.steps, lab)
        x.add("dabt_engine_active_slots", "gauge", "live decode slots", eng.num_active, lab)
        x.add("dabt_engine_queued_depth", "gauge", "accepted-but-unslotted requests", eng.queued_depth(), lab)
        x.add("dabt_engine_healthy", "gauge", "engine liveness predicate (1=serving)", sup["healthy"], lab)
        x.add("dabt_engine_degraded", "gauge", "restart circuit open", sup["degraded"], lab)
        x.add("dabt_engine_heartbeat_age_seconds", "gauge", "engine loop heartbeat age", sup["loop_heartbeat_age_s"], lab)
        x.add("dabt_engine_restarts_total", "counter", "crash-only engine restarts", sup["engine_restarts"], lab)
        x.add("dabt_engine_poisoned_requests_total", "counter", "requests quarantined as poison", sup["poisoned_requests"], lab)
        x.add("dabt_engine_circuit_trips_total", "counter", "restart-circuit trips", sup["circuit_trips"], lab)
        x.add("dabt_engine_restart_resubmitted_total", "counter", "token-less requests salvaged across restarts", sup["restarted_requests_resubmitted"], lab)
        x.add("dabt_engine_reclaimed_slots_total", "counter", "slots reclaimed before finish (deadline/cancel)", eng.reclaimed_slots, lab)
        dec_fn = getattr(eng, "decode_path_stats", None)
        if callable(dec_fn):
            # decode fast-path gauges (docs/QUANT.md): configured vs
            # effective fused-tick depth, weight format bits, and the
            # double-buffered upload fraction — the operator evidence that
            # the roofline knobs are actually engaged
            dec = dec_fn()
            x.add("dabt_decode_steps", "gauge", "configured fused decode-tick depth", dec.get("decode_steps"), lab)
            x.add("dabt_decode_steps_effective", "gauge", "decode steps the last tick actually ran (1 = json downgrade)", dec.get("decode_steps_effective"), lab)
            x.add("dabt_decode_json_downgraded_ticks_total", "counter", "fused ticks downgraded to single-step by live json slots", dec.get("json_downgraded_ticks"), lab)
            x.add("dabt_upload_overlap_frac", "gauge", "sampling/block-table upload cycles overlapped with an in-flight tick", dec.get("upload_overlap_frac"), lab)
            x.add("dabt_weight_bits", "gauge", "decode weight format width in bits (16/8/4)", dec.get("weight_bits"), lab)
            # continuous batching (docs/QUANT.md "Continuous batching"):
            # how often decode still waits on a sequential prefill chunk,
            # and how many chunks rode inside fused ticks instead
            x.add("dabt_prefill_displacement_frac", "gauge", "fraction of decode ticks displaced by a sequential prefill chunk", dec.get("prefill_displacement_frac"), lab)
            x.add("dabt_prefill_chunks_piggybacked_total", "counter", "prefill chunks run inside a fused decode tick", dec.get("prefill_chunks_piggybacked"), lab)
            x.add("dabt_prefill_piggyback", "gauge", "piggybacked-prefill program compiled for this engine", dec.get("prefill_piggyback"), lab)
            x.add("dabt_attn_fp8", "gauge", "fp8 in-dot decode attention engaged", dec.get("attn_fp8"), lab)
        sl_fn = getattr(eng, "slice_stats", None)
        if callable(sl_fn):
            # mesh-sliced fleet (docs/MULTICHIP.md): which devices this
            # replica's mesh spans and its device-resident HBM ledger — the
            # operator evidence that a replica's footprint lives only on its
            # slice (per-slice ledgers sum to the fleet footprint)
            sl = sl_fn()
            if sl.get("devices"):
                x.add("dabt_slice_devices", "gauge", "devices in this replica's mesh (its slice when pinned)", len(sl["devices"]), lab)
                x.add("dabt_slice_hbm_bytes", "gauge", "device-resident bytes on this replica's devices (weights + KV pool)", sl.get("hbm_bytes"), lab)
                x.add("dabt_slice_hbm_weight_bytes", "gauge", "device-resident weight bytes", sl.get("hbm_weight_bytes"), lab)
                x.add("dabt_slice_hbm_kv_bytes", "gauge", "device-resident KV pool/cache bytes", sl.get("hbm_kv_bytes"), lab)
            if sl.get("slice_id") is not None:
                x.add("dabt_slice_id", "gauge", "device-slice id this replica is pinned to", sl["slice_id"], lab)
        sched = getattr(eng, "scheduler", None)
        if sched is not None:
            st = sched.stats()
            x.add("dabt_sched_queue_depth", "gauge", "admission queue depth", st["queue_depth"], lab)
            x.add("dabt_sched_pressure", "gauge", "queue depth / max_queue", st["pressure"], lab)
            x.add("dabt_sched_est_wait_seconds", "gauge", "estimated queue wait", st["est_wait_s"], lab)
            x.add("dabt_sched_degraded", "gauge", "degradation band active", st["degraded"], lab)
            for reason, n in sorted(st["shed"].items()):
                x.add("dabt_sched_shed_total", "counter", "requests shed at admission, by reason", n, {**lab, "reason": reason})
            for cls, n in sorted(st["admitted"].items()):
                x.add("dabt_sched_admitted_total", "counter", "requests admitted, by class", n, {**lab, "class": cls})
        kv = eng.kv_stats()
        x.add("dabt_kv_prefix_hits_total", "counter", "prefix-cache hits", kv.get("prefix_hits"), lab)
        x.add("dabt_kv_prefix_misses_total", "counter", "prefix-cache misses", kv.get("prefix_misses"), lab)
        x.add("dabt_kv_pages_used", "gauge", "KV pool pages in use", kv.get("kv_pages_used"), lab)
        x.add("dabt_kv_pages_free", "gauge", "KV pool pages free", kv.get("kv_pages_free"), lab)
        x.add("dabt_kv_pages_total", "gauge", "KV pool size in pages", kv.get("kv_pages_total"), lab)
        if "kv_host_entries" in kv:
            # host/disk KV tier (docs/KV_PAGING.md "Tiered KV"): every tier
            # transition is also a flight event; these are the scrape side
            x.add("dabt_kv_tier_host_entries", "gauge", "warm prefixes resident in host DRAM", kv.get("kv_host_entries"), lab)
            x.add("dabt_kv_tier_host_bytes", "gauge", "host-tier bytes in use", kv.get("kv_host_bytes"), lab)
            x.add("dabt_kv_tier_host_pages", "gauge", "pages' worth of KV held in host DRAM", kv.get("kv_host_pages"), lab)
            x.add("dabt_kv_tier_disk_entries", "gauge", "warm prefixes demoted to disk", kv.get("kv_disk_entries"), lab)
            x.add("dabt_kv_tier_spills_total", "counter", "prefix entries spilled into the host tier", kv.get("kv_spills"), lab)
            x.add("dabt_kv_tier_restores_total", "counter", "host-tier entries restored into HBM pages", kv.get("kv_restores"), lab)
            x.add("dabt_kv_tier_restores_inflight", "gauge", "restores dispatched but not yet consumed by a prefill", kv.get("kv_restores_inflight"), lab)
            x.add("dabt_kv_tier_restore_p95_seconds", "gauge", "p95 host-visible restore dispatch latency", (kv.get("kv_restore_p95_ms") or 0.0) / 1e3, lab)
            x.add("dabt_kv_tier_dropped_total", "counter", "warm entries lost (budget/disk failure)", kv.get("kv_tier_dropped"), lab)
            x.add("dabt_kv_tier_migrated_in_total", "counter", "entries absorbed from detaching replicas", kv.get("kv_migrated_in"), lab)
        spec = eng.spec_stats() if callable(getattr(eng, "spec_stats", None)) else None
        if spec is not None:
            x.add("dabt_spec_drafted_total", "counter", "speculative tokens drafted", spec["spec_drafted"], lab)
            x.add("dabt_spec_accepted_total", "counter", "speculative tokens accepted", spec["spec_accepted"], lab)
            x.add("dabt_spec_accept_rate", "gauge", "cumulative speculative accept rate", spec["spec_accept_rate"], lab)
            # spec x fused: the controller's live rung and the scanned
            # verify depth — effective tokens/dispatch ceiling is
            # steps * (depth + 1) on a fully-accepting greedy row
            x.add("dabt_spec_tree_width", "gauge", "speculative tree width the controller currently issues", spec.get("spec_tree_width"), lab)
            x.add("dabt_spec_tree_depth", "gauge", "speculative tree depth (K) the controller currently issues", spec.get("spec_tree_depth"), lab)
            x.add("dabt_spec_verify_steps", "gauge", "scanned verify passes per speculative tick (decode_steps)", getattr(eng, "burst", 1), lab)
        obs = getattr(eng, "obs", None)
        if obs is not None:
            x.add_histogram("dabt_ttft_seconds", "time to first token (submit -> first host token)", obs.ttft_s, lab)
            x.add_histogram("dabt_itl_seconds", "inter-token latency (host batch-arrival gaps)", obs.itl_s, lab)
            x.add_histogram("dabt_queue_wait_seconds", "admission queue wait (submit -> prefill start)", obs.queue_wait_s, lab)
            x.add_histogram("dabt_tick_seconds", "decode tick result wait in _process_tick", obs.tick_s, lab)
            x.add_histogram("dabt_spec_tick_accept_ratio", "per-tick speculative accept ratio (greedy rows)", obs.accept_ratio, lab)
            x.add("dabt_traces_total", "counter", "completed request traces recorded", obs.traces_total, lab)
            x.add("dabt_flight_dumps_total", "counter", "flight-recorder dumps written", obs.flight.dumps, lab)
        if router is not None and id(router) not in routers_done:
            routers_done.add(id(router))
            rlab = {"model": model}
            rs = router.router_stats()
            x.add("dabt_router_replicas", "gauge", "replicas behind the router", rs["n_replicas"], rlab)
            x.add("dabt_router_reroutes_total", "counter", "token-less re-routes off failed replicas", rs["reroutes"], rlab)
            x.add("dabt_router_rerouted_failed_total", "counter", "re-routable failures past the hop budget", rs["rerouted_failed"], rlab)
            x.add("dabt_router_failed_past_first_token_total", "counter", "replica failures not re-routable (tokens emitted)", rs["failed_past_first_token"], rlab)
            x.add("dabt_router_no_replica_total", "counter", "submissions with no replica available", rs["no_replica_available"], rlab)
            x.add("dabt_router_drains_total", "counter", "replica drains", rs["drains"], rlab)
            x.add("dabt_router_replicas_added_total", "counter", "replicas added to the fleet (scale-up)", rs.get("replicas_added"), rlab)
            x.add("dabt_router_replicas_removed_total", "counter", "replicas drained and detached (scale-down)", rs.get("replicas_removed"), rlab)
            x.add("dabt_router_replica_restarts_total", "counter", "replica restarts (operator or drain-restart)", rs.get("replica_restarts"), rlab)
            x.add("dabt_router_affinity_hit_rate", "gauge", "prefix-affinity dispatch hit rate", rs["affinity_hit_rate"], rlab)
            if "slices_total" in rs:
                # sliced-fleet capacity: free slices == honest scale-up
                # headroom (0 free -> add_replica is a no_capacity rejection)
                x.add("dabt_router_slices_total", "gauge", "device slices planned on this host", rs["slices_total"], rlab)
                x.add("dabt_router_slices_free", "gauge", "device slices not pinned to a replica", rs["slices_free"], rlab)
                x.add("dabt_router_replica_devices", "gauge", "devices per replica slice", rs["replica_devices"], rlab)
            # fleet warm-state durability (scale-down migration; the
            # pages_lost counter is the pre-migration visibility satellite)
            x.add("dabt_kv_tier_pages_lost_at_detach_total", "counter", "warm KV pages dropped by replica detaches", rs.get("pages_lost_at_detach"), rlab)
            x.add("dabt_kv_tier_pages_migrated_total", "counter", "warm KV pages migrated at scale-down", rs.get("pages_migrated"), rlab)
            x.add("dabt_kv_tier_entries_migrated_total", "counter", "warm prefix entries migrated at scale-down", rs.get("entries_migrated"), rlab)
            preg = rs.get("prefix_registry")
            if preg:
                x.add("dabt_kv_fleet_prefixes", "gauge", "distinct warm prefixes known fleet-wide", preg.get("prefixes"), rlab)
                for tier in ("hbm", "host", "disk"):
                    x.add("dabt_kv_fleet_holdings", "gauge", "fleet prefix-registry holdings by tier", preg.get(tier), {**rlab, "tier": tier})
            for rep_stats in rs["replicas"]:
                plab = {"model": model, "replica": rep_stats["name"]}
                x.add("dabt_replica_draining", "gauge", "replica drain flag", rep_stats["draining"], plab)
                x.add("dabt_replica_breaker_open", "gauge", "router breaker not closed", rep_stats["breaker"] != "closed", plab)
                x.add("dabt_replica_dispatched_total", "counter", "requests dispatched to replica", rep_stats["dispatched"], plab)
    for model, asc in sorted(getattr(registry, "autoscalers", {}).items()):
        # SLO autoscaler (serving/autoscaler.py): every decision is
        # scrapeable — fleet size vs bounds, scale/degrade counters, and the
        # last control tick's raw signals
        lab = {"model": model}
        st = asc.stats()
        x.add("dabt_autoscale_replicas", "gauge", "current fleet size", st["replicas"], lab)
        x.add("dabt_autoscale_min_replicas", "gauge", "fleet floor", st["min_replicas"], lab)
        x.add("dabt_autoscale_max_replicas", "gauge", "fleet ceiling", st["max_replicas"], lab)
        x.add("dabt_autoscale_ticks_total", "counter", "control-loop iterations", st["ticks"], lab)
        x.add("dabt_autoscale_scale_ups_total", "counter", "replicas added by the controller", st["scale_ups"], lab)
        x.add("dabt_autoscale_scale_downs_total", "counter", "replicas removed by the controller", st["scale_downs"], lab)
        x.add("dabt_autoscale_scale_up_failures_total", "counter", "failed scale-up attempts", st["scale_up_failures"], lab)
        for reason, n in sorted(st.get("scale_up_skipped", {}).items()):
            # WHY a wanted scale-up was held back: no_capacity (slices
            # exhausted — at the hardware limit) vs cooldown (flap-damped)
            # vs bounds (the configured max_replicas ceiling)
            x.add("dabt_autoscale_scale_up_skipped_total", "counter", "overloaded ticks whose scale-up was held back, by reason", n, {**lab, "reason": reason})
        x.add("dabt_autoscale_at_hardware_limit", "gauge", "last scale-up attempt found no free device slice", st.get("at_hardware_limit"), lab)
        x.add("dabt_autoscale_degrade_active", "gauge", "load-adaptive degradation engaged", st["degrade_active"], lab)
        x.add("dabt_autoscale_degrade_engaged_total", "counter", "degradation band engagements", st["degrade_engaged"], lab)
        x.add("dabt_autoscale_replica_seconds_total", "counter", "integral of fleet size over time", st["replica_seconds"], lab)
        sig = st.get("last_signals", {})
        x.add("dabt_autoscale_slo_burn", "gauge", "last tick's p95 TTFT / SLO", sig.get("burn"), lab)
        x.add("dabt_autoscale_ttft_p95_seconds", "gauge", "last tick's observed p95 TTFT", sig.get("ttft_p95_s"), lab)
        x.add("dabt_autoscale_shed_rate", "gauge", "last tick's admission sheds per second", sig.get("shed_rate"), lab)
        x.add("dabt_autoscale_est_wait_seconds", "gauge", "last tick's worst predicted queue wait", sig.get("est_wait_s"), lab)
        x.add("dabt_autoscale_kv_frac", "gauge", "last tick's KV pool occupancy", sig.get("kv_frac"), lab)
    for model, emb in sorted(getattr(registry, "embedders", {}).items()):
        lab = {"model": model}
        x.add("dabt_embed_queue_depth", "gauge", "embedding coalescer queue depth", emb._queue.qsize(), lab)
        x.add("dabt_embed_shed_total", "counter", "embedding requests shed", getattr(emb, "shed", 0), lab)
    # cross-process fleet plane (serving/fleet.py, docs/FLEET.md): the server
    # side (every serve process has a plane) and — when this process also
    # fronts the fleet — the FleetRouter's dispatch counters
    plane = getattr(registry, "fleet_plane", None)
    if plane is not None:
        try:
            ps = plane.stats()
        except Exception:  # pragma: no cover - defensive scrape path
            ps = None
        if ps:
            flab = {"peer": ps.get("name", ""), "pool": ps.get("pool", "")}
            x.add("dabt_fleet_pool_info", "gauge", "fleet pool role of this process (labels carry identity)", 1, flab)
            x.add("dabt_fleet_gossip_seq", "counter", "prefix gossip delta-log sequence", ps.get("gossip_seq"), flab)
            x.add("dabt_fleet_kv_puts_total", "counter", "KV wire entries absorbed from peers", ps.get("kv_puts"), flab)
            x.add("dabt_fleet_kv_gets_total", "counter", "KV wire entries exported to peers", ps.get("kv_gets"), flab)
            x.add("dabt_fleet_kv_put_rejects_total", "counter", "KV wire entries refused at absorb", ps.get("kv_put_rejects"), flab)
            x.add("dabt_fleet_pages_in_total", "counter", "KV pages received over the fleet wire", ps.get("pages_in"), flab)
            x.add("dabt_fleet_pages_out_total", "counter", "KV pages shipped over the fleet wire", ps.get("pages_out"), flab)
            x.add("dabt_fleet_handoff_pushes_total", "counter", "prefill->decode handoff pushes", ps.get("pushes"), flab)
            x.add("dabt_fleet_handoff_push_failures_total", "counter", "failed handoff pushes", ps.get("push_failures"), flab)
            x.add("dabt_fleet_pool_rejects_total", "counter", "requests shed by the pool-role guard", ps.get("pool_rejects"), flab)
            x.add("dabt_fleet_pool_bypasses_total", "counter", "forced requests past the pool-role guard", ps.get("pool_bypasses"), flab)
            x.add("dabt_fleet_kv_integrity_rejects_total", "counter", "checksum-failed KV wire payloads rejected", ps.get("kv_integrity_rejects"), flab)
            x.add("dabt_fleet_idem_executions_total", "counter", "idempotency-keyed executions owned by this process", ps.get("idem_executions"), flab)
            x.add("dabt_fleet_idem_hits_total", "counter", "duplicate dispatches answered from the idempotency ledger", ps.get("idem_hits"), flab)
            x.add("dabt_fleet_idem_coalesced_total", "counter", "duplicate dispatches coalesced onto an in-flight execution", ps.get("idem_coalesced"), flab)
            x.add("dabt_fleet_idem_ledger_entries", "gauge", "live idempotency ledger entries", ps.get("idem_ledger"), flab)
    frouter = getattr(registry, "fleet_router", None)
    if frouter is not None:
        try:
            fs = frouter.stats()
        except Exception:  # pragma: no cover - defensive scrape path
            fs = None
        if fs:
            flab = {"model": fs.get("model", "")}
            x.add("dabt_fleet_peers_total", "gauge", "configured fleet peers", fs.get("peers_total"), flab)
            x.add("dabt_fleet_peers_healthy", "gauge", "fleet peers passing health refresh", fs.get("peers_healthy"), flab)
            x.add("dabt_fleet_reroutes_total", "counter", "token-less cross-peer re-routes", fs.get("reroutes"), flab)
            x.add("dabt_fleet_rerouted_failed_total", "counter", "requests failed after exhausting re-routes", fs.get("rerouted_failed"), flab)
            x.add("dabt_fleet_no_peer_available_total", "counter", "dispatches that found no live peer", fs.get("no_peer_available"), flab)
            x.add("dabt_fleet_affinity_hits_total", "counter", "dispatches landing on a prefix-holder peer", fs.get("affinity_hits"), flab)
            x.add("dabt_fleet_affinity_misses_total", "counter", "dispatches missing every holder peer", fs.get("affinity_misses"), flab)
            x.add("dabt_fleet_prefix_pulls_total", "counter", "cross-process prefix pulls completed", fs.get("prefix_pulls"), flab)
            x.add("dabt_fleet_pages_shipped_total", "counter", "KV pages shipped by pulls and handoffs", fs.get("pages_shipped"), flab)
            x.add("dabt_fleet_handoffs_total", "counter", "disaggregated prefill->decode handoffs", fs.get("handoffs"), flab)
            x.add("dabt_fleet_handoff_fallbacks_total", "counter", "handoffs that fell back to unified dispatch", fs.get("handoff_fallbacks"), flab)
            x.add("dabt_fleet_net_timeout_retries_total", "counter", "same-peer retries after a read-phase wire death", fs.get("timeout_retries"), flab)
            x.add("dabt_fleet_net_ttl_drops_total", "counter", "partitioned peers whose gossip holdings aged out", fs.get("ttl_drops"), flab)
            x.add("dabt_fleet_net_gossip_digest_mismatches_total", "counter", "diverged gossip logs forced onto the reset-snapshot path", fs.get("gossip_digest_mismatches"), flab)
            x.add("dabt_fleet_net_reconciles_total", "counter", "post-heal anti-entropy reconciliations completed", fs.get("reconciles"), flab)
            x.add("dabt_fleet_net_reconcile_last_seconds", "gauge", "last heal-to-converged reconciliation time", fs.get("reconcile_last_s"), flab)
            x.add("dabt_fleet_pull_integrity_rejects_total", "counter", "prefix pulls rejected by the receiver's checksum", fs.get("pull_integrity_rejects"), flab)
            x.add("dabt_fleet_pull_refetches_total", "counter", "prefix pulls re-fetched after a corrupt transfer", fs.get("pull_refetches"), flab)
            for reason, n in sorted((fs.get("refresh_failure_reasons") or {}).items()):
                x.add("dabt_fleet_refresh_failures_total", "counter", "peer refresh failures by classified reason", n, {"model": fs.get("model", ""), "reason": reason})
            for peer in fs.get("peers", []):
                plab = {"model": fs.get("model", ""), "peer": peer["name"], "pool": peer.get("pool", "")}
                x.add("dabt_fleet_peer_healthy", "gauge", "peer health from the last refresh", 1 if peer.get("healthy") else 0, plab)
                x.add("dabt_fleet_peer_dispatched_total", "counter", "requests dispatched to this peer", peer.get("dispatched"), plab)
                x.add("dabt_fleet_peer_ttl_dropped", "gauge", "peer currently aged out of the prefix registry", 1 if peer.get("ttl_dropped") else 0, plab)
    _render_task_plane(x)
    _render_rag_plane(x)
    return x.render()


# ------------------------------------------------------------- JSON logging
class JsonLogFormatter(logging.Formatter):
    """One JSON object per log line: ``ts``/``level``/``logger``/``event``
    plus any of the structured serving fields (``trace_id``, ``model``,
    ``replica``, ``reason``, ...) attached via ``logger.info(..., extra=...)``.
    (``record.created`` is stamped by the logging module itself — this
    formatter makes no time calls of its own.)"""

    FIELDS = ("trace_id", "model", "replica", "event", "reason", "site", "tenant")

    def format(self, record: logging.LogRecord) -> str:
        out: Dict[str, Any] = {
            "ts": round(record.created, 3),
            "level": record.levelname.lower(),
            "logger": record.name,
            "event": record.getMessage(),
        }
        for f in self.FIELDS:
            v = record.__dict__.get(f)
            if v is not None and f not in out:
                out[f] = v
        if record.exc_info and record.exc_info[0] is not None:
            out["exc"] = repr(record.exc_info[1])
        return json.dumps(out, ensure_ascii=False, default=str)


def setup_json_logging(*, force: bool = False, stream: Any = None) -> bool:
    """Opt-in structured logging for the serving process: ``DABT_LOG_JSON=1``
    (or ``--log-json`` / ``force=True``) swaps the root handler's formatter
    for :class:`JsonLogFormatter`.  Plain-text default is untouched when the
    gate is off.  Returns whether JSON logging is active."""
    if not force and os.environ.get(ENV_LOG_JSON, "").strip() not in ("1", "true", "yes"):
        return False
    root = logging.getLogger()
    if not root.handlers:
        handler = logging.StreamHandler(stream)
        root.addHandler(handler)
        root.setLevel(logging.INFO)
    for handler in root.handlers:
        handler.setFormatter(JsonLogFormatter())
    return True
