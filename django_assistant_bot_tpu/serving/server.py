"""TPU model server — the reference gpu_service's HTTP contract, aiohttp edition.

Endpoint parity (reference: gpu_service/main.py:75-107):

- ``POST /embeddings/`` ``{model, texts}`` -> ``{"embeddings": [[...], ...]}``
- ``POST /dialog/`` ``{model, messages, max_tokens, json_format}`` ->
  ``{"response": {"result": str, "usage": {...}, "length_limited": bool}}``
- 400 "Model is not supported" for unknown models; 500 with detail on failure.

Extras the reference lacks: ``GET /healthz`` (engine/slot stats) and ``GET /models``,
plus ``"stream": true`` on ``/dialog/`` — a ``text/event-stream`` response with
per-token delta events and a terminal usage event (wire format in
docs/STREAMING.md).  A mid-stream client disconnect cancels the engine request,
which frees its decode slot within one tick.  The non-streaming path is
byte-identical to before (the bench baseline).
One process, one mesh, engines shared across all requests — the continuous batcher
gives cross-request batching instead of gunicorn worker replicas.
"""

from __future__ import annotations

import asyncio
import json
import logging
import math
import re
from typing import Any, Mapping, Optional

from aiohttp import web

from .engine import EngineUnavailable
from .kv_pool import WireIntegrityError, WireVersionError
from .obs import new_trace_id, rag_plane_snapshot, render_prometheus
from .registry import ModelRegistry
from .scheduler import DeadlineExceeded, SchedulerRejected

logger = logging.getLogger(__name__)

REGISTRY_KEY: web.AppKey[ModelRegistry] = web.AppKey("registry", ModelRegistry)
DRAIN_KEY: web.AppKey[dict] = web.AppKey("drain_state", dict)
FLEET_KEY: web.AppKey[Any] = web.AppKey("fleet_plane", object)

MAX_MAX_TOKENS = 1 << 17  # sanity ceiling; engines clamp to max_seq_len anyway
PRIORITIES = ("interactive", "background")

# client-supplied X-Request-Id values are echoed into headers and bodies:
# only token-safe shapes pass through (anything else — or nothing — gets a
# generated id), so a hostile header cannot smuggle CR/LF or grow unbounded
_REQ_ID_RE = re.compile(r"^[A-Za-z0-9._:-]{1,64}$")

# fleet idempotency keys (trace_id:attempt) share the token-safe shape but
# allow a little more length for the appended attempt ordinal
_IDEM_KEY_RE = re.compile(r"^[A-Za-z0-9._:-]{1,80}$")


def _request_id(request: web.Request) -> str:
    """The request's correlation id: the client's ``X-Request-Id`` when it is
    token-safe, else a fresh trace id.  Echoed on EVERY ``/dialog/`` response
    shape (JSON, SSE terminal event, 4xx/5xx error bodies) so a shed 429 and
    the client retry that follows correlate by one id."""
    rid = request.headers.get("X-Request-Id", "").strip()
    if rid and _REQ_ID_RE.match(rid):
        return rid
    return new_trace_id()


def _draining_response(rid: Optional[str] = None) -> web.Response:
    """Graceful shutdown in progress: stop admitting, finish in-flight work.
    New requests get an honest 503 + Retry-After instead of being accepted
    and then killed mid-generation by process exit."""
    body = {"detail": "server draining for shutdown"}
    headers = {"Retry-After": "2"}
    if rid is not None:
        body["request_id"] = rid
        headers["X-Request-Id"] = rid
    return web.json_response(body, status=503, headers=headers)


class _BadRequest(ValueError):
    """Validation failure carrying the client-facing detail message."""


def _validate_sampling(body: Mapping[str, Any]) -> tuple:
    """Pull and range-check the sampling knobs.  NaN/negative/huge values used
    to flow straight into the device sampler (NaN temperature poisons the
    whole batched softmax row); they are a 422 now."""
    temperature = body.get("temperature", 0.8)
    top_p = body.get("top_p", 0.95)
    max_tokens = body.get("max_tokens", 1024)
    if isinstance(temperature, bool) or not isinstance(temperature, (int, float)):
        raise _BadRequest("temperature must be a number")
    temperature = float(temperature)
    if not math.isfinite(temperature) or not (0.0 <= temperature <= 2.0):
        raise _BadRequest("temperature must be finite and within [0, 2]")
    if isinstance(top_p, bool) or not isinstance(top_p, (int, float)):
        raise _BadRequest("top_p must be a number")
    top_p = float(top_p)
    if not math.isfinite(top_p) or not (0.0 < top_p <= 1.0):
        raise _BadRequest("top_p must be finite and within (0, 1]")
    if isinstance(max_tokens, bool) or not isinstance(max_tokens, int):
        raise _BadRequest("max_tokens must be an integer")
    if not (1 <= max_tokens <= MAX_MAX_TOKENS):
        raise _BadRequest(f"max_tokens must be within [1, {MAX_MAX_TOKENS}]")
    return temperature, top_p, max_tokens


def _scheduling_fields(
    request: web.Request, body: Mapping[str, Any]
) -> tuple[str, str, Optional[float]]:
    """Priority class, fair-share tenant and deadline: body fields win,
    ``X-Priority`` / ``X-Tenant`` / ``X-Deadline-S`` headers are the fallback
    (so proxies can tag traffic without rewriting bodies)."""
    priority = body.get("priority", request.headers.get("X-Priority", "interactive"))
    if priority not in PRIORITIES:
        raise _BadRequest(f"priority must be one of {list(PRIORITIES)}")
    tenant = body.get("tenant", request.headers.get("X-Tenant", "default"))
    if not isinstance(tenant, str) or not tenant.strip() or len(tenant) > 128:
        raise _BadRequest("tenant must be a non-empty string of <= 128 chars")
    deadline_s = body.get("deadline_s", request.headers.get("X-Deadline-S"))
    if deadline_s is not None:
        try:
            deadline_s = float(deadline_s)
        except (TypeError, ValueError):
            raise _BadRequest("deadline_s must be a number") from None
        if not math.isfinite(deadline_s) or not (0.0 < deadline_s <= 3600.0):
            raise _BadRequest("deadline_s must be finite and within (0, 3600]")
    return priority, tenant.strip(), deadline_s


def _with_rid(body: dict, rid: Optional[str], headers: Optional[dict] = None):
    """(body, headers) with the correlation id riding both (None = no id)."""
    headers = dict(headers or {})
    if rid is not None:
        body["request_id"] = rid
        headers["X-Request-Id"] = rid
    return body, headers


def _shed_response(e: SchedulerRejected, rid: Optional[str] = None) -> web.Response:
    """Load shed -> 429 with a Retry-After back-off hint."""
    retry = max(1, math.ceil(e.retry_after_s))
    body, headers = _with_rid(
        {"detail": str(e), "reason": e.reason, "retry_after_s": e.retry_after_s},
        rid,
        {"Retry-After": str(retry)},
    )
    return web.json_response(body, status=429, headers=headers)


def _unavailable_response(
    e: EngineUnavailable, rid: Optional[str] = None
) -> web.Response:
    """Engine restart circuit open -> 503 with a Retry-After covering the
    remaining degraded cooldown (docs/RESILIENCE.md)."""
    retry = max(1, math.ceil(e.retry_after_s))
    body, headers = _with_rid(
        {"detail": str(e), "retry_after_s": e.retry_after_s},
        rid,
        {"Retry-After": str(retry)},
    )
    return web.json_response(body, status=503, headers=headers)


def _error_response(detail: str, status: int, rid: str) -> web.Response:
    body, headers = _with_rid({"detail": detail}, rid)
    return web.json_response(body, status=status, headers=headers)


def _usage(model: str, result) -> dict:
    return result.usage_dict(model)


def _sse(payload) -> bytes:
    data = payload if isinstance(payload, str) else json.dumps(payload)
    return f"data: {data}\n\n".encode("utf-8")


async def _stream_dialog(
    request: web.Request, eng, model: str, messages, rid: str, **gen_kwargs
) -> web.StreamResponse:
    """``"stream": true`` -> ``text/event-stream`` (wire format in
    docs/STREAMING.md): one ``data:`` event per emitted text delta, a terminal
    event carrying finish reason + usage + the full result text, then a
    literal ``[DONE]``.

    The FIRST chunk is awaited before the response is prepared so synchronous
    failures (load shed, infeasible deadline, bad request) still map to their
    proper HTTP statuses; later failures surface as an ``error`` event on the
    open stream.  A client disconnect mid-stream abandons the generator, whose
    cleanup cancels the engine request — the per-iteration reap then frees the
    decode slot within one tick (the deadline epoch mechanism)."""
    agen = eng.generate_stream(messages, trace_id=rid, **gen_kwargs)
    try:
        first = await agen.__anext__()
    except StopAsyncIteration:
        first = None
    except SchedulerRejected as e:
        return _shed_response(e, rid)
    except EngineUnavailable as e:
        return _unavailable_response(e, rid)
    except DeadlineExceeded as e:
        return _error_response(str(e), 504, rid)
    except Exception as e:
        logger.exception("stream dialog failed before first token")
        return _error_response(str(e), 500, rid)

    resp = web.StreamResponse(
        status=200,
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
            "X-Request-Id": rid,
        },
    )
    await resp.prepare(request)
    try:
        chunk = first
        while chunk is not None:
            if chunk.done:
                if chunk.text:  # flushed hold-back tail rides its own event
                    await resp.write(
                        _sse({"delta": chunk.text, "index": chunk.index})
                    )
                r = chunk.result
                await resp.write(
                    _sse(
                        {
                            "done": True,
                            "finish_reason": chunk.finish_reason,
                            "result": r.text,
                            "usage": _usage(model, r),
                            "length_limited": r.length_limited,
                            "request_id": rid,
                        }
                    )
                )
                break
            if chunk.text:
                await resp.write(_sse({"delta": chunk.text, "index": chunk.index}))
            try:
                chunk = await agen.__anext__()
            except StopAsyncIteration:
                break
        await resp.write(_sse("[DONE]"))
        await resp.write_eof()
    except (
        asyncio.CancelledError,
        ConnectionResetError,
        ConnectionError,
    ):
        # client went away mid-stream; the finally's aclose() cancels the
        # engine request so its slot frees within one decode tick
        logger.info("stream client disconnected mid-generation")
        raise
    except Exception as e:
        # already committed to 200: surface the failure as an error event
        logger.exception("stream dialog failed mid-stream")
        try:
            await resp.write(
                _sse(
                    {
                        "done": True,
                        "finish_reason": "error",
                        "error": str(e),
                        "request_id": rid,
                    }
                )
            )
            await resp.write(_sse("[DONE]"))
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError):
            pass
    finally:
        await agen.aclose()
    return resp


def create_app(
    registry: ModelRegistry, *, drain_deadline_s: float = 30.0
) -> web.Application:
    app = web.Application()
    app[REGISTRY_KEY] = registry
    # graceful-drain state (the SIGTERM path, docs/RESILIENCE.md): once the
    # flag flips, admission endpoints 503 and on_shutdown waits — bounded by
    # drain_deadline_s — for every engine to finish what it already accepted
    # before on_cleanup stops the engines (which fails anything left).
    drain = {"draining": False, "deadline_s": float(drain_deadline_s)}
    app[DRAIN_KEY] = drain

    async def embeddings(request: web.Request) -> web.Response:
        if drain["draining"]:
            return _draining_response()
        try:
            body = await request.json()
            model, texts = body["model"], body["texts"]
            if not isinstance(model, str):
                raise ValueError("model must be a string")
            if not isinstance(texts, list) or not all(isinstance(t, str) for t in texts):
                raise ValueError("texts must be a list of strings")
        except Exception:
            return web.json_response({"detail": "invalid request"}, status=422)
        eng = registry.get_embedder(model)
        if eng is None:
            return web.json_response({"detail": "Model is not supported"}, status=400)
        try:
            embs = await eng.embed(texts)
            return web.json_response({"embeddings": embs})
        except SchedulerRejected as e:
            return _shed_response(e)
        except Exception as e:
            logger.exception("embeddings failed")
            return web.json_response({"detail": str(e)}, status=500)

    async def dialog(request: web.Request) -> web.Response:
        rid = _request_id(request)
        if drain["draining"]:
            return _draining_response(rid)
        try:
            body = await request.json()
            model = body["model"]
            if not isinstance(model, str):
                raise ValueError("model must be a string")
            messages = body["messages"]
            json_format = bool(body.get("json_format", False))
            stream = body.get("stream", False)
            if not isinstance(stream, bool):
                raise _BadRequest("stream must be a boolean")
            if stream and json_format:
                # documented choice (docs/STREAMING.md): constrained-JSON
                # output is only validated as a whole document, and partial
                # JSON is not independently consumable — reject rather than
                # pretend chunks are usable
                raise _BadRequest(
                    "stream is not supported with json_format; "
                    "request one or the other"
                )
            temperature, top_p, max_tokens = _validate_sampling(body)
            priority, tenant, deadline_s = _scheduling_fields(request, body)
        except _BadRequest as e:
            return _error_response(str(e), 422, rid)
        except Exception:
            return _error_response("invalid request", 422, rid)
        eng = registry.get_generator(model)
        if eng is None:
            return _error_response("Model is not supported", 400, rid)
        if stream:
            return await _stream_dialog(
                request,
                eng,
                model,
                messages,
                rid,
                max_tokens=max_tokens,
                temperature=temperature,
                top_p=top_p,
                priority=priority,
                tenant=tenant,
                deadline_s=deadline_s,
            )
        try:
            # json_format enables grammar-constrained decoding: a JSON token-FSM
            # masks sampling inside the decode tick (ops/json_fsm.py), so the
            # output is valid JSON in one shot even at high temperature — the
            # reference instead retries with an LLM repair loop
            # (assistant/ai/providers/ollama.py:49-107)
            result = await eng.generate(
                messages,
                max_tokens=max_tokens,
                temperature=temperature,
                top_p=top_p,
                json_format=json_format,
                priority=priority,
                tenant=tenant,
                deadline_s=deadline_s,
                trace_id=rid,
            )
            return web.json_response(
                {
                    "response": {
                        "result": result.text,
                        "usage": _usage(model, result),
                        "length_limited": result.length_limited,
                    },
                    "request_id": rid,
                },
                headers={"X-Request-Id": rid},
            )
        except SchedulerRejected as e:
            return _shed_response(e, rid)
        except EngineUnavailable as e:
            return _unavailable_response(e, rid)
        except DeadlineExceeded as e:
            return _error_response(str(e), 504, rid)
        except Exception as e:
            logger.exception("dialog failed")
            return _error_response(str(e), 500, rid)

    async def healthz(request: web.Request) -> web.Response:
        # status degrades when ANY generator is unhealthy: restart circuit
        # open, engine thread dead, or a loop heartbeat older than the
        # threshold (a wedged XLA call used to keep reporting green here)
        status = "draining" if drain["draining"] else "ok"
        generators = {}
        for name, eng in registry.generators.items():
            g = {
                "active_slots": eng.num_active,
                "steps": eng.steps,
                "reclaimed_slots": getattr(eng, "reclaimed_slots", 0),
            }
            latency = getattr(eng, "latency_stats", None)
            if callable(latency):
                # TTFT / inter-token-latency percentiles + disconnect count —
                # the streaming plane's perceived-latency dashboard
                g["stream"] = latency()
            kv = getattr(eng, "kv_stats", None)
            if callable(kv):
                # KV memory plane gauges: pool occupancy, shared-page
                # fraction, allocator eviction/COW counters (docs/KV_PAGING.md)
                g["kv"] = kv()
            sl = getattr(eng, "slice_stats", None)
            if callable(sl):
                # mesh-sliced fleet (docs/MULTICHIP.md): slice identity +
                # per-slice HBM ledger per replica; routers add the planner's
                # total/free slice capacity (scale-up headroom)
                g["slices"] = sl()
            dec = getattr(eng, "decode_path_stats", None)
            if callable(dec):
                # decode fast-path gauges (docs/QUANT.md): fused-tick depth
                # configured vs effective (json downgrade), weight bits, and
                # the double-buffered upload fraction — which fast path is
                # ACTUALLY active, same pattern as kv_layout_effective
                g["decode"] = dec()
            spec = getattr(eng, "spec_stats", None)
            if callable(spec):
                # speculative-decoding gauges: accept rate/EMA (per tree
                # arm), the rung in use, and load- vs acceptance-disable —
                # None (omitted) on non-speculative engines
                sv = spec()
                if sv is not None:
                    g["spec"] = sv
            sched = getattr(eng, "scheduler", None)
            if sched is not None:
                # queue depth, shed counters, per-class wait percentiles —
                # the operator's overload dashboard (KV-pressure sheds appear
                # under sched.shed.kv_pressure, distinct from queue_full)
                g["sched"] = sched.stats()
            router = getattr(eng, "router_stats", None)
            if callable(router):
                # multi-replica fleet gauges: per-replica depth/breaker,
                # affinity hit rate, re-routes, drains, scale events
                # (serving/router.py)
                g["router"] = router()
            asc = getattr(registry, "autoscalers", {}).get(name)
            if asc is not None:
                # SLO autoscaler: current band/decision, fleet bounds, scale
                # and degradation counters (serving/autoscaler.py)
                g["autoscaler"] = asc.stats()
            sup = getattr(eng, "supervision_stats", None)
            if callable(sup):
                # restart/quarantine/circuit counters + loop_heartbeat_age_s
                # (routers aggregate: one unhealthy replica of N degrades the
                # fleet status, with per-replica blocks under "replicas")
                g["supervision"] = sv = sup()
                if not sv.get("healthy", True) and status == "ok":
                    status = "degraded"
            generators[name] = g
        payload = {
            "status": status,
            "models": sorted(registry.specs),
            "generators": generators,
            "embedders": {
                name: {
                    "queue_depth": eng._queue.qsize(),
                    "max_queue": getattr(eng, "max_queue", 0),
                    "shed": getattr(eng, "shed", 0),
                    "dropped_cancelled": getattr(eng, "dropped_cancelled", 0),
                }
                for name, eng in registry.embedders.items()
            },
        }
        # RAG plane (when this process has built vector indexes): per-index
        # engine kind + the ANN recall/drift gauges (docs/ANN.md)
        rag = rag_plane_snapshot()
        if rag.get("indexes"):
            # durability roll-up across WAL+snapshot-backed indexes
            # (docs/DURABILITY.md): one block an operator can alert on
            # without walking per-index stats.  A corrupt-snapshot fallback
            # or a lost WAL flock degrades health — both mean the durable
            # plane is serving, but not the way it was configured to.
            durables = [
                (name, st["durability"])
                for name, st in sorted(rag["indexes"].items())
                if isinstance(st, dict) and st.get("durability")
            ]
            if durables:
                ages = [d["snapshot_age_s"] for _, d in durables if d.get("snapshot_age_s") is not None]
                rag["durability"] = {
                    "indexes": len(durables),
                    "writable": sum(1 for _, d in durables if d.get("writable")),
                    "wal_records": sum(int(d.get("wal_records") or 0) for _, d in durables),
                    "wal_bytes": sum(int(d.get("wal_bytes") or 0) for _, d in durables),
                    "oldest_snapshot_age_s": max(ages) if ages else None,
                    "replayed_records": sum(int(d.get("replayed_records") or 0) for _, d in durables),
                    "snapshot_fallbacks": sum(int(d.get("snapshot_fallbacks") or 0) for _, d in durables),
                    "torn_tail_truncations": sum(int(d.get("torn_tail_truncations") or 0) for _, d in durables),
                }
                if rag["durability"]["snapshot_fallbacks"] and status == "ok":
                    payload["status"] = status = "degraded"
            payload["rag"] = rag
        return web.json_response(payload)

    async def models(request: web.Request) -> web.Response:
        return web.json_response(
            {
                name: {"kind": spec.kind, "path": spec.path, "tiny": spec.tiny}
                for name, spec in registry.specs.items()
            }
        )

    async def metrics(request: web.Request) -> web.Response:
        # Prometheus text exposition (docs/OBSERVABILITY.md).  Deliberately
        # NOT gated on the drain flag: a draining/degraded fleet is exactly
        # when the scrape matters.  render_prometheus is a pure read path —
        # every stats surface does its own fine-grained locking, and no
        # router lock is ever held across an engine call (the PR 7 ABBA
        # family; witness-covered by the CI obs smoke).
        try:
            text = render_prometheus(registry)
        except Exception:
            logger.exception("/metrics render failed")
            return web.Response(status=500, text="metrics render failed")
        return web.Response(
            body=text.encode("utf-8"),
            headers={"Content-Type": "text/plain; version=0.0.4; charset=utf-8"},
        )

    # ---------------------------------------------------------- fleet plane
    # The cross-process wire (serving/fleet.py, docs/FLEET.md).  A plane
    # attached by the CLI (pool role, peer list) is reused; otherwise a
    # default unified plane is created so every serve process speaks the
    # fleet protocol out of the box.
    plane = getattr(registry, "fleet_plane", None)
    if plane is None:
        from .fleet import FleetPlane

        plane = FleetPlane(registry)
        registry.fleet_plane = plane
    app[FLEET_KEY] = plane

    def _validate_prompt_ids(body: Mapping[str, Any]) -> list:
        ids = body.get("prompt_ids")
        if (
            not isinstance(ids, list)
            or not ids
            or len(ids) > MAX_MAX_TOKENS
            or not all(
                isinstance(t, int) and not isinstance(t, bool) and t >= 0
                for t in ids
            )
        ):
            raise _BadRequest(
                "prompt_ids must be a non-empty list of non-negative ints"
            )
        return ids

    async def fleet_generate(request: web.Request) -> web.Response:
        """Token-level dialog contract for FleetRouter peers: prompt_ids in,
        token_ids + usage out (detokenized text rides along).  Honors the
        same sampling/scheduling validation as /dialog/, plus the fleet
        extras: prefix_len (warm-prefix restore), prefill_only + push_to
        (the disaggregated handoff), and force (pool-role bypass)."""
        rid = _request_id(request)
        if drain["draining"]:
            return _draining_response(rid)
        try:
            body = await request.json()
            model = body["model"]
            if not isinstance(model, str):
                raise _BadRequest("model must be a string")
            prompt_ids = _validate_prompt_ids(body)
            temperature, top_p, max_tokens = _validate_sampling(body)
            priority, tenant, deadline_s = _scheduling_fields(request, body)
            json_format = bool(body.get("json_format", False))
            prefill_only = bool(body.get("prefill_only", False))
            force = bool(body.get("force", False))
            push_to = body.get("push_to")
            if push_to is not None and not isinstance(push_to, str):
                raise _BadRequest("push_to must be a string URL")
            prefix_len = body.get("prefix_len", 0)
            if (
                isinstance(prefix_len, bool)
                or not isinstance(prefix_len, int)
                or prefix_len < 0
            ):
                raise _BadRequest("prefix_len must be a non-negative integer")
            trace_id = body.get("trace_id") or rid
            if not isinstance(trace_id, str) or not _REQ_ID_RE.match(trace_id):
                trace_id = rid
            idem_key = body.get("idem_key")
            if idem_key is not None and (
                not isinstance(idem_key, str)
                or not _IDEM_KEY_RE.match(idem_key)
            ):
                idem_key = None  # malformed keys never gate execution
        except _BadRequest as e:
            return _error_response(str(e), 422, rid)
        except Exception:
            return _error_response("invalid request", 422, rid)
        eng = registry.get_generator(model)
        if eng is None:
            return _error_response("Model is not supported", 400, rid)
        # idempotent dispatch: a timeout-retry carrying the same key gets the
        # ORIGINAL result back (or coalesces onto the in-flight execution)
        # instead of re-executing — double execution is the failure the chaos
        # bench counts to zero
        idem_fut = None
        if idem_key is not None:
            for _ in range(2):
                state, f = plane.idem_claim(idem_key)
                if state == "mine":
                    idem_fut = f
                    break
                prior = await asyncio.wrap_future(f)
                if prior is not None:
                    return web.json_response(
                        {**prior, "deduped": True, "request_id": rid},
                        headers={"X-Request-Id": rid},
                    )
                # the owning execution failed and released — claim afresh
        completed = False
        try:
            rej = plane.admission_guard(
                model,
                eng,
                prompt_ids,
                prefix_len,
                prefill_only=prefill_only,
                force=force,
            )
            if rej is not None:
                return _shed_response(rej, rid)
            if prefill_only:
                # the handoff contract: full-prefix chunked prefill, one token
                # emitted, background class — the scheduler tag that keeps
                # handoff traffic distinct from interactive decode
                max_tokens = 1
                temperature = 0.0
                priority = "background"
                prefix_len = max(prefix_len, len(prompt_ids) - 1)
            try:
                fut = eng.submit(
                    prompt_ids,
                    max_tokens=max_tokens,
                    temperature=temperature,
                    top_p=top_p,
                    json_format=json_format,
                    prefix_len=prefix_len,
                    priority=priority,
                    tenant=tenant,
                    deadline_s=deadline_s,
                    trace_id=trace_id,
                )
                result = await asyncio.wrap_future(fut)
            except SchedulerRejected as e:
                return _shed_response(e, rid)
            except EngineUnavailable as e:
                return _unavailable_response(e, rid)
            except DeadlineExceeded as e:
                return _error_response(str(e), 504, rid)
            except ValueError as e:
                return _error_response(str(e), 422, rid)
            except Exception as e:
                logger.exception("fleet generate failed")
                return _error_response(str(e), 500, rid)
            resp = {
                "token_ids": [int(t) for t in result.token_ids],
                "result": result.text,
                "usage": _usage(model, result),
                "length_limited": result.length_limited,
                "request_id": rid,
                "trace_id": trace_id,
            }
            if prefill_only:
                # export + push the finished prefix pages off the event loop
                resp["handoff"] = await asyncio.get_running_loop().run_in_executor(
                    None, plane.handoff_export, model, prompt_ids, prefix_len, push_to
                )
            if idem_fut is not None:
                plane.idem_complete(idem_key, idem_fut, resp)
                completed = True
            return web.json_response(resp, headers={"X-Request-Id": rid})
        finally:
            # every non-success exit (shed, 5xx, deadline, cancellation)
            # releases the ledger entry so a retry re-executes cleanly
            if idem_fut is not None and not completed:
                plane.idem_release(idem_key, idem_fut)

    async def fleet_healthz(request: web.Request) -> web.Response:
        check = request.query.get("peers", "1") not in ("0", "false")
        body = await asyncio.get_running_loop().run_in_executor(
            None, lambda: plane.healthz(check_peers=check)
        )
        if drain["draining"]:
            body["status"] = "draining"
        return web.json_response(body)

    async def fleet_prefix(request: web.Request) -> web.Response:
        try:
            since = int(request.query.get("since", "0"))
        except ValueError:
            return web.json_response(
                {"detail": "since must be an integer"}, status=422
            )
        return web.json_response(plane.prefix_events(since))

    async def fleet_kv_get(request: web.Request) -> web.Response:
        # deliberately NOT drain-gated: page migration off a draining peer
        # is exactly when this endpoint matters
        try:
            body = await request.json()
            model = body["model"]
            if not isinstance(model, str):
                raise _BadRequest("model must be a string")
            prompt_ids = _validate_prompt_ids(body)
            prefix_len = body.get("prefix_len", 0)
            if (
                isinstance(prefix_len, bool)
                or not isinstance(prefix_len, int)
                or prefix_len < 0
            ):
                raise _BadRequest("prefix_len must be a non-negative integer")
        except _BadRequest as e:
            return web.json_response({"detail": str(e)}, status=422)
        except Exception:
            return web.json_response({"detail": "invalid request"}, status=422)
        try:
            data = await asyncio.get_running_loop().run_in_executor(
                None, plane.kv_get_wire, model, prompt_ids, prefix_len
            )
        except KeyError:
            return web.json_response(
                {"detail": "Model is not supported"}, status=400
            )
        except Exception as e:
            logger.exception("fleet kv get failed")
            return web.json_response({"detail": str(e)}, status=500)
        if data is None:
            return web.json_response({"detail": "no matching prefix"}, status=404)
        return web.Response(
            body=data, content_type="application/octet-stream"
        )

    async def fleet_kv_put(request: web.Request) -> web.Response:
        model = request.query.get("model", "")
        data = await request.read()
        try:
            out = await asyncio.get_running_loop().run_in_executor(
                None, plane.kv_put_wire, model, data
            )
        except WireVersionError as e:
            # cross-build peer: fail loudly, never absorb pages we cannot
            # prove we understand (the versioned-wire contract)
            return web.json_response({"detail": str(e)}, status=409)
        except WireIntegrityError as e:
            # checksum-failed payload: machine-readable reason so the
            # puller's one-re-fetch-then-cold-prefill policy can key off it
            return web.json_response(
                {"detail": str(e), "reason": "wire_integrity"}, status=422
            )
        except KeyError:
            return web.json_response(
                {"detail": "Model is not supported"}, status=400
            )
        except ValueError as e:
            return web.json_response({"detail": str(e)}, status=422)
        except Exception as e:
            logger.exception("fleet kv put failed")
            return web.json_response({"detail": str(e)}, status=500)
        return web.json_response(out)

    async def traces(request: web.Request) -> web.Response:
        """Obs trace rings across every engine, flattened — the surface the
        trace-export CLI replays through workload/ (cli/trace_export.py)."""
        return web.json_response({"traces": plane.collect_traces()})

    app.router.add_post("/embeddings/", embeddings)
    app.router.add_post("/embeddings", embeddings)
    app.router.add_post("/dialog/", dialog)
    app.router.add_post("/dialog", dialog)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/models", models)
    app.router.add_post("/fleet/generate", fleet_generate)
    app.router.add_get("/fleet/healthz", fleet_healthz)
    app.router.add_get("/fleet/prefix", fleet_prefix)
    app.router.add_post("/fleet/kv/get", fleet_kv_get)
    app.router.add_post("/fleet/kv/put", fleet_kv_put)
    app.router.add_get("/traces", traces)

    async def on_shutdown(app):
        # SIGTERM graceful drain: web.run_app's signal handling triggers
        # app.shutdown() BEFORE on_cleanup, while in-flight handlers still
        # run.  Stop admission (the endpoints 503 via the flag), then wait —
        # deadline-bounded — for every engine to finish what it accepted, so
        # the on_cleanup stop() below finds nothing to kill.  A single
        # --replicas 1 engine drains exactly the same way; routers
        # additionally stop their own dispatch fleet-wide.
        drain["draining"] = True
        for eng in registry.generators.values():
            begin = getattr(eng, "begin_drain", None)
            if callable(begin):
                # routers stop their own dispatch too (non-blocking mark;
                # the poll below is the single wait loop)
                begin()
        deadline = asyncio.get_running_loop().time() + drain["deadline_s"]
        while asyncio.get_running_loop().time() < deadline:
            if registry.idle():
                logger.info("graceful drain complete; shutting down")
                return
            await asyncio.sleep(0.05)
        logger.warning(
            "graceful drain deadline (%.1fs) expired with work in flight; "
            "remaining requests fail on engine stop",
            drain["deadline_s"],
        )

    async def on_cleanup(app):
        registry.stop()

    app.on_shutdown.append(on_shutdown)
    app.on_cleanup.append(on_cleanup)
    return app


def load_config_file(path: str) -> Mapping[str, Any]:
    """TOML or JSON model config: ``[models.<name>] kind=... path=...``."""
    import json

    if path.endswith(".toml"):
        import tomllib

        with open(path, "rb") as f:
            data = tomllib.load(f)
    else:
        with open(path) as f:
            data = json.load(f)
    return data.get("models", data)


def run_server(
    config_path: str | None = None,
    *,
    host: str = "0.0.0.0",
    port: int = 11435,
    registry: ModelRegistry | None = None,
    drain_deadline_s: float = 30.0,
):
    """Blocking entry (CLI ``serve``).  Default port matches the reference
    (11435).  SIGTERM/SIGINT trigger a graceful drain: admission stops (503),
    in-flight work finishes within ``drain_deadline_s``, then the process
    exits 0 — a rolling restart sheds nothing instead of killing mid-stream
    generations."""
    if registry is None:
        config = load_config_file(config_path) if config_path else {}
        registry = ModelRegistry.from_config(config)
    web.run_app(
        create_app(registry, drain_deadline_s=drain_deadline_s),
        host=host,
        port=port,
    )
