"""TPU model server — the reference gpu_service's HTTP contract, aiohttp edition.

Endpoint parity (reference: gpu_service/main.py:75-107):

- ``POST /embeddings/`` ``{model, texts}`` -> ``{"embeddings": [[...], ...]}``
- ``POST /dialog/`` ``{model, messages, max_tokens, json_format}`` ->
  ``{"response": {"result": str, "usage": {...}, "length_limited": bool}}``
- 400 "Model is not supported" for unknown models; 500 with detail on failure.

Extras the reference lacks: ``GET /healthz`` (engine/slot stats) and ``GET /models``.
One process, one mesh, engines shared across all requests — the continuous batcher
gives cross-request batching instead of gunicorn worker replicas.
"""

from __future__ import annotations

import logging
from typing import Any, Mapping

from aiohttp import web

from .registry import ModelRegistry

logger = logging.getLogger(__name__)

REGISTRY_KEY: web.AppKey[ModelRegistry] = web.AppKey("registry", ModelRegistry)


def create_app(registry: ModelRegistry) -> web.Application:
    app = web.Application()
    app[REGISTRY_KEY] = registry

    async def embeddings(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            model, texts = body["model"], body["texts"]
            if not isinstance(model, str):
                raise ValueError("model must be a string")
            if not isinstance(texts, list) or not all(isinstance(t, str) for t in texts):
                raise ValueError("texts must be a list of strings")
        except Exception:
            return web.json_response({"detail": "invalid request"}, status=422)
        eng = registry.get_embedder(model)
        if eng is None:
            return web.json_response({"detail": "Model is not supported"}, status=400)
        try:
            embs = await eng.embed(texts)
            return web.json_response({"embeddings": embs})
        except Exception as e:
            logger.exception("embeddings failed")
            return web.json_response({"detail": str(e)}, status=500)

    async def dialog(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            model = body["model"]
            if not isinstance(model, str):
                raise ValueError("model must be a string")
            messages = body["messages"]
            max_tokens = int(body.get("max_tokens", 1024))
            json_format = bool(body.get("json_format", False))
            temperature = float(body.get("temperature", 0.8))
            top_p = float(body.get("top_p", 0.95))
        except Exception:
            return web.json_response({"detail": "invalid request"}, status=422)
        eng = registry.get_generator(model)
        if eng is None:
            return web.json_response({"detail": "Model is not supported"}, status=400)
        try:
            # json_format enables grammar-constrained decoding: a JSON token-FSM
            # masks sampling inside the decode tick (ops/json_fsm.py), so the
            # output is valid JSON in one shot even at high temperature — the
            # reference instead retries with an LLM repair loop
            # (assistant/ai/providers/ollama.py:49-107)
            result = await eng.generate(
                messages,
                max_tokens=max_tokens,
                temperature=temperature,
                top_p=top_p,
                json_format=json_format,
            )
            usage = {
                "model": model,
                "prompt_tokens": result.prompt_tokens,
                "completion_tokens": result.completion_tokens,
                "total_tokens": result.prompt_tokens + result.completion_tokens,
                "ttft_s": result.ttft_s,
                "latency_s": result.latency_s,
            }
            return web.json_response(
                {
                    "response": {
                        "result": result.text,
                        "usage": usage,
                        "length_limited": result.length_limited,
                    }
                }
            )
        except Exception as e:
            logger.exception("dialog failed")
            return web.json_response({"detail": str(e)}, status=500)

    async def healthz(request: web.Request) -> web.Response:
        return web.json_response(
            {
                "status": "ok",
                "models": sorted(registry.specs),
                "generators": {
                    name: {"active_slots": eng.num_active, "steps": eng.steps}
                    for name, eng in registry.generators.items()
                },
            }
        )

    async def models(request: web.Request) -> web.Response:
        return web.json_response(
            {
                name: {"kind": spec.kind, "path": spec.path, "tiny": spec.tiny}
                for name, spec in registry.specs.items()
            }
        )

    app.router.add_post("/embeddings/", embeddings)
    app.router.add_post("/embeddings", embeddings)
    app.router.add_post("/dialog/", dialog)
    app.router.add_post("/dialog", dialog)
    app.router.add_get("/healthz", healthz)
    app.router.add_get("/models", models)

    async def on_cleanup(app):
        registry.stop()

    app.on_cleanup.append(on_cleanup)
    return app


def load_config_file(path: str) -> Mapping[str, Any]:
    """TOML or JSON model config: ``[models.<name>] kind=... path=...``."""
    import json

    if path.endswith(".toml"):
        import tomllib

        with open(path, "rb") as f:
            data = tomllib.load(f)
    else:
        with open(path) as f:
            data = json.load(f)
    return data.get("models", data)


def run_server(
    config_path: str | None = None,
    *,
    host: str = "0.0.0.0",
    port: int = 11435,
    registry: ModelRegistry | None = None,
):
    """Blocking entry (CLI ``serve``).  Default port matches the reference (11435)."""
    if registry is None:
        config = load_config_file(config_path) if config_path else {}
        registry = ModelRegistry.from_config(config)
    web.run_app(create_app(registry), host=host, port=port)
