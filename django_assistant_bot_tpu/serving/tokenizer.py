"""Tokenisation for the serving plane.

Wraps HF fast tokenizers when a checkpoint directory ships one; falls back to a
byte-level tokenizer (vocab 256 + specials) so every code path — engine, server,
providers, tests — runs without any tokenizer asset.  Also owns chat-prompt
construction: HF chat templates when available, else the reference's plain
``"role: content"`` join (reference: assistant/ai/providers/transformers.py:50).
"""

from __future__ import annotations

from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> List[int]: ...
    def encode_chat(self, messages: Sequence[dict]) -> List[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat(self, messages: Sequence[dict]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are bytes; 256=pad, 257=bos, 258=eos."""

    vocab_size = 259
    pad_id = 256
    bos_id = 257
    eos_id = 258
    # decode == UTF-8 of the concatenated token_bytes(): the streaming
    # detokenizer may use its O(1)-per-token incremental-codec fast path
    byte_level = True

    def encode(self, text: str) -> List[int]:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def token_bytes(self) -> List[bytes]:
        """Exact bytes per id (constrained decoding); specials render nothing."""
        return [bytes([i]) for i in range(256)] + [b"", b"", b""]

    def apply_chat(self, messages: Sequence[dict]) -> str:
        return render_plain_chat(messages)

    def encode_chat(self, messages: Sequence[dict]) -> List[int]:
        return self.encode(self.apply_chat(messages))


class HFTokenizer:
    """Wrapper over a transformers fast tokenizer loaded from a model directory."""

    def __init__(self, tok):
        self._tok = tok
        self.eos_id = tok.eos_token_id if tok.eos_token_id is not None else -1
        pad = tok.pad_token_id
        self.pad_id = pad if pad is not None else (self.eos_id if self.eos_id >= 0 else 0)
        self.vocab_size = len(tok)  # incl. added tokens — ids the model can emit

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat(self, messages: Sequence[dict]) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                list(messages), tokenize=False, add_generation_prompt=True
            )
        return render_plain_chat(messages)

    def encode_chat(self, messages: Sequence[dict]) -> List[int]:
        """Chat templates already render BOS text — encode without special tokens to
        avoid the classic double-BOS degradation."""
        if getattr(self._tok, "chat_template", None):
            return self._tok.encode(self.apply_chat(messages), add_special_tokens=False)
        return self.encode(render_plain_chat(messages))


def encode_chat_split(tok: Tokenizer, messages: Sequence[dict]) -> tuple[List[int], int]:
    """Encode a chat and report how many leading tokens form a stable prefix.

    The prefix covers every message before the last (system prompt + history +
    packed RAG context — the block the engine's prefix KV cache can reuse
    across requests).  Correctness-first: the split is only reported when the
    prefix's own encoding is EXACTLY a prefix of the full encoding (BPE merges
    can straddle the boundary; then 0 is returned and the engine simply
    prefills in full)."""
    ids = tok.encode_chat(messages)
    if len(messages) < 2:
        return ids, 0
    head = list(messages[:-1])
    try:
        inner = getattr(tok, "_tok", None)
        if inner is not None and getattr(inner, "chat_template", None):
            prefix_str = inner.apply_chat_template(
                head, tokenize=False, add_generation_prompt=False
            )
            prefix_ids = _encode_head_cached(
                tok, prefix_str, lambda: inner.encode(prefix_str, add_special_tokens=False)
            )
        else:
            prefix_str = "\n".join(f"{m['role']}: {m['content']}" for m in head) + "\n"
            prefix_ids = _encode_head_cached(tok, prefix_str, lambda: tok.encode(prefix_str))
    except Exception:
        return ids, 0
    n = len(prefix_ids)
    if 0 < n < len(ids) and ids[:n] == prefix_ids:
        return ids, n
    return ids, 0


def _encode_head_cached(tok, prefix_str: str, encode) -> List[int]:
    """Memoize the shared head's encoding on the tokenizer instance.

    The prefix-KV workload re-sends a near-identical multi-kilobyte head every
    turn; without this the hot path tokenizes that head twice per request
    (full prompt + verification encode).  Small LRU per tokenizer; falls back
    to plain encode on objects that refuse attributes (e.g. __slots__)."""
    try:
        cache = tok.__dict__.setdefault("_head_encode_cache", {})
    except AttributeError:
        return encode()
    hit = cache.get(prefix_str)
    if hit is not None:
        return hit
    out = encode()
    if len(cache) >= 64:
        cache.clear()  # tiny, regenerable; wholesale reset beats LRU plumbing
    cache[prefix_str] = out
    return out


def render_plain_chat(messages: Sequence[dict]) -> str:
    """The reference's prompt construction: newline-joined "role: content" plus a
    trailing assistant cue (reference: assistant/ai/providers/transformers.py:50)."""
    lines = [f"{m['role']}: {m['content']}" for m in messages]
    lines.append("assistant:")
    return "\n".join(lines)


def load_tokenizer(model_dir: Optional[str]) -> Tokenizer:
    """HF tokenizer if the directory has one, else the byte fallback."""
    if model_dir:
        try:
            from transformers import AutoTokenizer

            return HFTokenizer(AutoTokenizer.from_pretrained(model_dir))
        except Exception:
            pass
    return ByteTokenizer()
