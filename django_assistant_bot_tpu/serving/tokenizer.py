"""Tokenisation for the serving plane.

Wraps HF fast tokenizers when a checkpoint directory ships one; falls back to a
byte-level tokenizer (vocab 256 + specials) so every code path — engine, server,
providers, tests — runs without any tokenizer asset.  Also owns chat-prompt
construction: HF chat templates when available, else the reference's plain
``"role: content"`` join (reference: assistant/ai/providers/transformers.py:50).
"""

from __future__ import annotations

from typing import List, Optional, Protocol, Sequence


class Tokenizer(Protocol):
    eos_id: int
    pad_id: int

    def encode(self, text: str) -> List[int]: ...
    def encode_chat(self, messages: Sequence[dict]) -> List[int]: ...
    def decode(self, ids: Sequence[int]) -> str: ...
    def apply_chat(self, messages: Sequence[dict]) -> str: ...


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids 0..255 are bytes; 256=pad, 257=bos, 258=eos."""

    vocab_size = 259
    pad_id = 256
    bos_id = 257
    eos_id = 258

    def encode(self, text: str) -> List[int]:
        return [self.bos_id] + list(text.encode("utf-8"))

    def decode(self, ids: Sequence[int]) -> str:
        return bytes(i for i in ids if 0 <= i < 256).decode("utf-8", errors="replace")

    def token_bytes(self) -> List[bytes]:
        """Exact bytes per id (constrained decoding); specials render nothing."""
        return [bytes([i]) for i in range(256)] + [b"", b"", b""]

    def apply_chat(self, messages: Sequence[dict]) -> str:
        return render_plain_chat(messages)

    def encode_chat(self, messages: Sequence[dict]) -> List[int]:
        return self.encode(self.apply_chat(messages))


class HFTokenizer:
    """Wrapper over a transformers fast tokenizer loaded from a model directory."""

    def __init__(self, tok):
        self._tok = tok
        self.eos_id = tok.eos_token_id if tok.eos_token_id is not None else -1
        pad = tok.pad_token_id
        self.pad_id = pad if pad is not None else (self.eos_id if self.eos_id >= 0 else 0)
        self.vocab_size = len(tok)  # incl. added tokens — ids the model can emit

    def encode(self, text: str) -> List[int]:
        return self._tok.encode(text)

    def decode(self, ids: Sequence[int]) -> str:
        return self._tok.decode(list(ids), skip_special_tokens=True)

    def apply_chat(self, messages: Sequence[dict]) -> str:
        if getattr(self._tok, "chat_template", None):
            return self._tok.apply_chat_template(
                list(messages), tokenize=False, add_generation_prompt=True
            )
        return render_plain_chat(messages)

    def encode_chat(self, messages: Sequence[dict]) -> List[int]:
        """Chat templates already render BOS text — encode without special tokens to
        avoid the classic double-BOS degradation."""
        if getattr(self._tok, "chat_template", None):
            return self._tok.encode(self.apply_chat(messages), add_special_tokens=False)
        return self.encode(render_plain_chat(messages))


def render_plain_chat(messages: Sequence[dict]) -> str:
    """The reference's prompt construction: newline-joined "role: content" plus a
    trailing assistant cue (reference: assistant/ai/providers/transformers.py:50)."""
    lines = [f"{m['role']}: {m['content']}" for m in messages]
    lines.append("assistant:")
    return "\n".join(lines)


def load_tokenizer(model_dir: Optional[str]) -> Tokenizer:
    """HF tokenizer if the directory has one, else the byte fallback."""
    if model_dir:
        try:
            from transformers import AutoTokenizer

            return HFTokenizer(AutoTokenizer.from_pretrained(model_dir))
        except Exception:
            pass
    return ByteTokenizer()
