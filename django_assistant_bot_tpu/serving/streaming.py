"""Token streaming primitives: engine tick -> async consumer, UTF-8-safe.

The serving plane used to be strictly request/response: the engine samples
tokens tick-by-tick, but no layer could observe a partial generation, so the
user stares at a typing indicator for the full generation wall time and TTFT
was unmeasurable end-to-end.  This module is the bridge:

- :class:`TokenStream` — a per-request bounded event queue fed from the engine
  thread as already-in-flight device results resolve in ``_process_tick``
  (piggybacking on the existing async ``_TickRef`` consumption: pushing a
  sampled id is a deque append, NO new blocking ``device_get`` per token) and
  drained by one asyncio consumer.  The producer never blocks — capacity is
  ``max_tokens + 2``, which the generation can never exceed — so a slow SSE
  client cannot throttle the decode tick.
- :class:`IncrementalDetokenizer` — streaming decode that never emits a
  replacement character for an incomplete multi-byte/BPE fragment: partial
  sequences are held back and flushed once completed.  The concatenation of
  every emitted delta is byte-identical to the one-shot decode of the same
  ids.
- :class:`StreamChunk` — one event of ``GenerationEngine.generate_stream()``:
  a token delta, or the terminal chunk carrying the finish reason and the
  full :class:`~.engine.GenerationResult`.

Cancellation contract: abandoning the ``generate_stream`` iterator (client
disconnect) cancels the request's future; the engine's per-iteration reap
(:meth:`GenerationEngine._reap_dead_slots` — the deadline epoch mechanism)
frees the decode slot within one tick instead of burning the rest of the
generation on a consumer nobody is reading.  See docs/STREAMING.md.
"""

from __future__ import annotations

import asyncio
import codecs
import collections
import dataclasses
import logging
import threading
from concurrent.futures import CancelledError, Future
from typing import Any, AsyncIterator, List, Optional, Tuple

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class StreamChunk:
    """One streaming event.

    ``index`` is the 0-based generated-token index; ``text`` the UTF-8-safe
    delta (may be ``""`` while a multi-byte fragment is held back).  The
    terminal chunk has ``done=True``, the ``finish_reason`` (``"stop"`` on
    EOS, ``"length"`` when length-limited), any held-back text tail, and the
    full :class:`~.engine.GenerationResult` — whose ``text`` equals the
    concatenation of every ``text`` delta, byte for byte."""

    index: int
    token_id: Optional[int]
    text: str
    done: bool = False
    finish_reason: Optional[str] = None
    result: Any = None


class TokenStream:
    """Thread-safe producer (engine thread) -> single async consumer bridge.

    The engine side (:meth:`push_token`, :meth:`finish`) only appends under a
    lock and pokes the consumer's loop via ``call_soon_threadsafe`` — no
    waiting, no device work.  ``finish`` is wired as the request future's
    done-callback, so EVERY resolution path (normal finish, deadline expiry,
    engine failure, client cancel) terminates the stream exactly once.
    """

    def __init__(self) -> None:
        self._events: "collections.deque[Tuple[str, Any]]" = collections.deque()
        self._lock = threading.Lock()
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None
        self._capacity: Optional[int] = None
        self._closed = False
        # coalesced wakeups: one call_soon_threadsafe per consumer drain
        # cycle, not per token — cross-thread notification is the only
        # non-trivial producer cost and a burst tick pushes many tokens
        self._notify_pending = False
        self.dropped = 0  # defensive only: capacity covers max_tokens + terminal

    def bind(self, loop: asyncio.AbstractEventLoop, capacity: int) -> "TokenStream":
        """Attach the consumer's event loop.  ``capacity`` bounds queued token
        events; callers size it ``max_tokens + 2`` so the producer can never
        hit the bound (the generation itself is shorter)."""
        self._loop = loop
        self._wake = asyncio.Event()
        self._capacity = max(1, int(capacity))
        return self

    # --------------------------------------------------------- producer side
    def push_token(self, tok: int, *, notify: bool = True) -> bool:
        """Append a token event.  With ``notify=False`` the wakeup is the
        caller's responsibility (:meth:`notify_now`) — the engine defers it to
        the end of its tick processing so a burst of pushes costs ONE
        cross-thread wakeup per stream per tick, fired right before the
        engine thread goes back to (GIL-releasing) device work instead of
        mid-bookkeeping where the handoff stalls it.  Returns True when a
        deferred wakeup is owed."""
        with self._lock:
            if self._closed:
                return False
            if self._capacity is not None and len(self._events) >= self._capacity:
                # unreachable when capacity >= max_tokens + 1; never block the
                # engine thread on a consumer — drop and count instead
                self.dropped += 1
                return False
            self._events.append(("token", tok))
            need_notify = not self._notify_pending
            self._notify_pending = True
        if need_notify and notify:
            self._notify()
            return False
        return need_notify

    def notify_now(self) -> None:
        """Deliver a wakeup deferred by ``push_token(notify=False)``."""
        self._notify()

    def finish(self, fut: Future) -> None:
        """Future done-callback: terminal event (result or exception)."""
        if fut.cancelled():
            payload: Any = CancelledError()
        else:
            exc = fut.exception()
            payload = exc if exc is not None else fut.result()
        with self._lock:
            if self._closed:
                return
            self._closed = True
            self._events.append(("done", payload))
            self._notify_pending = True
        # terminal always notifies: it must never coalesce into a wakeup the
        # consumer already consumed
        self._notify()

    def _notify(self) -> None:
        loop, wake = self._loop, self._wake
        if loop is None or wake is None:
            return
        try:
            loop.call_soon_threadsafe(wake.set)
        except RuntimeError:
            pass  # consumer loop already closed; events stay queued, unread

    # --------------------------------------------------------- consumer side
    async def __aiter__(self) -> AsyncIterator[Tuple[str, Any]]:
        assert self._wake is not None, "bind() the consumer loop before iterating"
        while True:
            self._wake.clear()
            with self._lock:
                batch = list(self._events)
                self._events.clear()
                closed = self._closed
                # drained: the next producer append must schedule a wakeup
                self._notify_pending = False
            for ev in batch:
                yield ev
                if ev[0] == "done":
                    return
            if closed:
                return
            await self._wake.wait()


class IncrementalDetokenizer:
    """UTF-8-safe streaming decode: hold back incomplete fragments, flush on
    completion; the concatenated output is byte-identical to the one-shot
    decode of the same ids.

    Two paths:

    - **byte-level** (``tokenizer.byte_level``, e.g. :class:`ByteTokenizer`):
      each id maps to raw bytes (``token_bytes()``) and decode is plain UTF-8
      of the concatenation — an incremental UTF-8 codec holds partial
      multi-byte sequences exactly like the one-shot ``errors="replace"``
      decode would resolve them.  O(1) per token.
    - **general** (HF/BPE): re-decode the full id list and emit the suffix
      past what was already emitted, holding back any *trailing* U+FFFD run
      (an in-flight byte-fallback sequence the next token may complete).
      O(n) decode per token — bounded by ``max_tokens``, and the decode of a
      few-hundred-token list is microseconds on HF fast tokenizers.
    """

    def __init__(self, tokenizer) -> None:
        self._tok = tokenizer
        self._byte_table: Optional[List[bytes]] = None
        if getattr(tokenizer, "byte_level", False):
            tb = getattr(tokenizer, "token_bytes", None)
            if callable(tb):
                self._byte_table = tb()
        if self._byte_table is not None:
            self._dec = codecs.getincrementaldecoder("utf-8")("replace")
        else:
            self._ids: List[int] = []
            self._emitted = ""
            self._warned = False

    def push(self, tok: int) -> str:
        """Feed one token id; return the newly-safe text delta (may be "")."""
        if self._byte_table is not None:
            b = self._byte_table[tok] if 0 <= tok < len(self._byte_table) else b""
            return self._dec.decode(b)
        self._ids.append(tok)
        full = self._tok.decode(self._ids)
        if not full.startswith(self._emitted):
            # non-prefix-stable decode (pathological tokenizer): stop emitting
            # mid-stream; flush() reconciles against the final full decode
            return ""
        delta = full[len(self._emitted):]
        while delta.endswith("�"):
            delta = delta[:-1]
        self._emitted += delta
        return delta

    def flush(self) -> str:
        """Emit everything still held back (end of generation)."""
        if self._byte_table is not None:
            return self._dec.decode(b"", True)
        full = self._tok.decode(self._ids) if self._ids else ""
        if full.startswith(self._emitted):
            delta = full[len(self._emitted):]
        else:  # pragma: no cover - non-prefix-stable decode; keep totals honest
            if not self._warned:
                self._warned = True
                logger.warning(
                    "incremental detokenizer: decode is not prefix-stable; "
                    "final delta reconciled against the one-shot decode"
                )
            delta = full
            self._emitted = ""
        self._emitted += delta
        return delta
