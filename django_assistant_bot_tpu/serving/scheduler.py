"""Admission-controlled request scheduler for the serving plane.

The engines used to feed from an unbounded FIFO ``queue.Queue()``: every
``/dialog/`` request was accepted unconditionally, and a burst of background
ingestion traffic (question/sentence generation, embedding batches) could
starve interactive dialog turns indefinitely.  The reference pushed the same
problem onto Celery queues between services; a single-process TPU batcher
needs its own scheduler — the standard shape in production LLM serving stacks
(vLLM-style continuous batching with admission control, Orca-style
iteration-level scheduling).

This module is deliberately engine-agnostic: it orders and admits anything
exposing ``.future``, ``.submitted_at``, ``.priority``, ``.tenant`` and
``.deadline_at`` (the engine's ``_Request`` does), so every policy is unit
testable without a device.

Policies, in one place:

- **Priority classes.**  Requests carry a class tag (``interactive`` dialog >
  ``background`` ingestion/embedding), propagated end-to-end from the provider
  layer and HTTP headers.  Classes share by *weight* (default 8:1), not strict
  priority — background work cannot be starved forever, but interactive turns
  take ~8 of every 9 free slots under contention.
- **Weighted per-tenant fair share.**  Within a class, tenants (workspaces)
  interleave by stride scheduling over virtual time: one chatty tenant cannot
  monopolize slots.  Both levels collapse into a single stride: each
  ``(class, tenant)`` queue advances its virtual *pass* by
  ``1 / (class_weight * tenant_weight)`` per admitted request and the lowest
  pass runs next — the classic deterministic approximation of weighted fair
  queueing.
- **Deadlines.**  A request may carry an absolute deadline; expired entries
  are dropped at the queue head (future fails with :class:`DeadlineExceeded`)
  and the engine reaps expired *running* slots so an expired request stops
  burning decode ticks (see ``GenerationEngine._reap_dead_slots``).
- **Overload behavior.**  The queue is bounded; past the bound — or past an
  estimated-wait ceiling derived from an EMA of observed service times —
  submission fails *synchronously* with :class:`SchedulerRejected` carrying a
  ``retry_after_s`` hint (HTTP 429 + ``Retry-After`` at the server).  Between
  "fine" and "shed" there is a degradation band: past ``degrade_at`` queue
  pressure the scheduler clamps ``max_tokens`` and asks the engine to disable
  speculative decoding (its verify forward is wasted work at low acceptance).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, Dict, List, Mapping, Optional, Tuple

INTERACTIVE = "interactive"
BACKGROUND = "background"


class SchedulerRejected(RuntimeError):
    """Load shed: the request was NOT queued.  ``retry_after_s`` is the
    client back-off hint (HTTP 429 + ``Retry-After``)."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(f"request shed: {reason} (retry after {retry_after_s:.1f}s)")
        self.reason = reason
        self.retry_after_s = float(retry_after_s)


class DeadlineExceeded(RuntimeError):
    """The request's deadline passed before it finished; its queue entry (or
    live decode slot) was reclaimed."""


@dataclasses.dataclass
class SchedulerConfig:
    # bound on queued-but-not-yet-slotted requests (the admission queue; live
    # decode slots are bounded separately by the engine's max_slots)
    max_queue: int = 256
    # class name -> weight; unknown classes get weight 1.  Weighted share,
    # not strict priority: background drains at weight/(sum) under contention.
    class_weights: Mapping[str, float] = dataclasses.field(
        default_factory=lambda: {INTERACTIVE: 8.0, BACKGROUND: 1.0}
    )
    # tenant name -> weight within its class (unlisted tenants get 1.0)
    tenant_weights: Mapping[str, float] = dataclasses.field(default_factory=dict)
    # estimated-wait admission ceiling: shed when the queue's estimated wait
    # exceeds this (None disables the test; the depth bound still applies)
    admit_max_wait_s: Optional[float] = 60.0
    # predictive admission (docs/AUTOSCALING.md): when the engine's queue-wait
    # histogram (obs plane) holds at least admit_hist_min_samples RECENT
    # observations, the estimated wait is the MAX of the depth*EMA/slots model
    # and this quantile of *realized* queue waits — the empirical tail the
    # point EMA cannot see (service-time variance, multi-slot effects).  The
    # histogram is bound via bind_wait_hist(); cold histograms fall back to
    # the EMA model alone.  The quantile is computed over a two-window
    # rotation of the histogram's counts (rotated every admit_hist_window
    # samples), NOT its process lifetime — an overload hours ago must not
    # inflate predictions (and 429 Retry-After hints) at today's light load.
    admit_wait_quantile: float = 0.95
    admit_hist_min_samples: int = 32
    admit_hist_window: int = 2048
    # deadline applied when the client sends none (None = no deadline)
    default_deadline_s: Optional[float] = None
    # graceful degradation band: past this fraction of max_queue, clamp
    # max_tokens and disable speculative decoding; 1.0 disables the band
    degrade_at: float = 0.75
    degrade_max_tokens: int = 256
    # per-request service-time EMA seed (seconds) for the estimated-wait test
    # before any request has finished; decays fast once real finishes arrive
    service_time_init: float = 1.0
    service_time_alpha: float = 0.2
    # wait-time sample window per class for the p50/p95 health stats
    wait_window: int = 512

    @classmethod
    def from_knobs(cls, **kw) -> "SchedulerConfig":
        """Build from flat ModelSpec-style knobs, ignoring Nones."""
        return cls(**{k: v for k, v in kw.items() if v is not None})


@dataclasses.dataclass
class Admission:
    ok: bool
    reason: str = ""
    retry_after_s: float = 0.0
    # degradation: clamp max_tokens to this when set (queue pressure band)
    clamp_max_tokens: Optional[int] = None


class RequestScheduler:
    """Two-level weighted fair queue with bounded admission.

    Thread contract: :meth:`try_admit`, :meth:`note_service`, :meth:`stats`
    and the counters are safe from any thread (one internal lock);
    :meth:`enqueue` / :meth:`peek` / :meth:`pop` / :meth:`drain` mutate the
    queue structure and are engine-thread-only (they still take the lock so
    the cross-thread counters stay coherent).
    """

    def __init__(
        self,
        cfg: Optional[SchedulerConfig] = None,
        *,
        slots: int = 8,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg or SchedulerConfig()
        self._slots = max(1, int(slots))
        self._clock = clock
        self._lock = threading.Lock()
        self._queues: Dict[Tuple[str, str], Deque] = {}
        self._pass: Dict[Tuple[str, str], float] = {}
        self._vtime = 0.0
        self._depth = 0
        # KV-pressure admission (paged KV plane, docs/KV_PAGING.md): the
        # engine binds a callable reporting the pool's obtainable pages; the
        # scheduler tracks pages already promised to queued requests so a
        # burst cannot over-commit the pool between admissions
        self._kv_available = None
        self._kv_total = 0
        self._queued_kv_pages = 0
        self._spec_gauge_fn = None  # engine's spec_disabled gauge (bind_spec)
        self._kv_tier_stats_fn = None  # host KV tier stats (bind_kv_tier)
        # queue-wait histogram (obs plane) for predictive admission; None
        # keeps the pure EMA model (bind_wait_hist).  The windowing state
        # (last rotation's raw-count mark + the completed previous window)
        # has its own lock: _hist_wait_q runs OUTSIDE the scheduler lock and
        # must still rotate atomically across admitting threads.
        self._wait_hist = None
        self._hist_lock = threading.Lock()
        self._hist_mark: Optional[list] = None
        self._hist_prev: Optional[list] = None
        # autoscaler degradation override (set_degrade): when set, the
        # degradation band is forced on regardless of queue pressure — the
        # clamp applies at admission and degraded() reports True, which also
        # makes the engine skip speculative verify forwards
        self._degrade_forced: Optional[int] = None
        self._service_ema_s = float(self.cfg.service_time_init)
        # per-token service model (note_service with tokens > 0): rate EMA x
        # tokens-per-request EMA replaces the raw per-request EMA once warm,
        # so fused N-step decode ticks don't inflate predicted queue waits
        self._service_per_token_ema_s: Optional[float] = None
        self._service_tokens_ema = 0.0
        # per-class counters (created lazily so new classes just appear)
        self.submitted: Dict[str, int] = collections.defaultdict(int)
        self.admitted: Dict[str, int] = collections.defaultdict(int)
        self.shed: Dict[str, int] = collections.defaultdict(int)  # by reason
        self.expired_queued: Dict[str, int] = collections.defaultdict(int)
        self.expired_running: Dict[str, int] = collections.defaultdict(int)
        self.cancelled_queued: Dict[str, int] = collections.defaultdict(int)
        self._waits: Dict[str, Deque[float]] = collections.defaultdict(
            lambda: collections.deque(maxlen=self.cfg.wait_window)
        )

    # ------------------------------------------------------------- admission
    def bind_slots(self, slots: int) -> "RequestScheduler":
        """Engine capacity for the estimated-wait model (est wait =
        depth * service_ema / slots)."""
        self._slots = max(1, int(slots))
        return self

    def bind_spec(self, gauge_fn) -> "RequestScheduler":
        """Wire the engine's speculative-disable gauge into :meth:`stats`:
        the degradation band already reports ``degraded`` (load-disable);
        with this bound, operators also see the acceptance-controller's
        verdict side by side (``spec_disabled``) and can tell load-disable
        from acceptance-disable without cross-referencing engine stats."""
        self._spec_gauge_fn = gauge_fn
        return self

    def bind_wait_hist(self, hist) -> "RequestScheduler":
        """Wire the obs plane's queue-wait histogram into admission: once it
        holds ``admit_hist_min_samples``, the estimated wait (and therefore
        the shed test and the 429 ``Retry-After`` hint) is floored by the
        ``admit_wait_quantile`` of *realized* waits instead of trusting the
        point service-time EMA alone.  ``hist`` needs ``.count`` and
        ``.quantile(q)`` (serving/obs.py :class:`~.obs.Histogram`)."""
        self._wait_hist = hist
        return self

    def set_degrade(self, clamp_max_tokens: Optional[int]) -> None:
        """Force the degradation band on (``clamp_max_tokens``) or release it
        (``None``) — the autoscaler's load-shaping actuator.  While forced,
        :meth:`try_admit` clamps ``max_tokens`` and :meth:`degraded` reports
        True (which also makes the engine skip speculative verify forwards),
        independent of the queue-pressure band."""
        with self._lock:
            self._degrade_forced = (
                None if clamp_max_tokens is None else max(1, int(clamp_max_tokens))
            )

    def _hist_wait_q(self) -> Optional[float]:
        """The WINDOWED wait quantile, or None (cold / unbound).

        Quantiles the previous + current window of the histogram's raw
        counts (two-window rotation every ``admit_hist_window`` samples), so
        the prediction tracks recent traffic instead of the histogram's
        process lifetime.  Called OUTSIDE self._lock: the histogram does its
        own locking, the rotation state its own — no lock is ever nested."""
        from .obs import quantile_from_counts

        h = self._wait_hist
        if h is None:
            return None
        cfg = self.cfg
        with self._hist_lock:
            # the snapshot read happens INSIDE the rotation lock: two
            # admitting threads interleaving "read snapshot / rotate mark"
            # would otherwise diff against a NEWER mark and produce negative
            # window counts (a garbage ~30s quantile).  Lock order is
            # _hist_lock -> Histogram._lock only; nothing acquires them the
            # other way.
            counts, _n = h.raw_counts()
            if self._hist_mark is None:
                self._hist_mark = [0] * len(counts)
            cur = [c - m for c, m in zip(counts, self._hist_mark)]
            eff = (
                cur
                if self._hist_prev is None
                else [a + b for a, b in zip(cur, self._hist_prev)]
            )
            if sum(cur) >= cfg.admit_hist_window:
                # rotate: the current window becomes "previous", so there is
                # always up to 2x window of recent history behind the estimate
                self._hist_prev = cur
                self._hist_mark = counts
        if sum(eff) < cfg.admit_hist_min_samples:
            return None
        return float(quantile_from_counts(h.bounds, eff, cfg.admit_wait_quantile))

    def bind_kv(self, available_fn, total_pages: int) -> "RequestScheduler":
        """Wire the paged-KV pool into admission: ``available_fn`` reports
        obtainable pages (free + evictable cached prefixes), ``total_pages``
        the pool size.  A request that cannot start now — and whose projected
        KV wait (queued-KV backlog in pool drains x the service-time EMA)
        exceeds ``admit_max_wait_s`` — sheds with the distinct ``kv_pressure``
        reason instead of queueing behind memory that frees no faster than
        running requests finish."""
        self._kv_available = available_fn
        self._kv_total = max(0, int(total_pages))
        return self

    def bind_kv_tier(self, stats_fn) -> "RequestScheduler":
        """Wire the host/disk KV tier's stats into :meth:`stats` (the
        ``bind_spec`` discipline: the gauge callable runs OUTSIDE this
        scheduler's lock — it takes the tier's own lock).  Operators and the
        autoscaler then read pool pressure (``queued_kv_pages``, sheds) and
        warm-tier depth (``kv_tier.kv_host_entries`` / bytes) side by side:
        a pool under pressure with a deep warm tier sheds *restorable* work,
        one without sheds *unrecoverable* prefill."""
        self._kv_tier_stats_fn = stats_fn
        return self

    def release_kv(self, pages: int) -> None:
        """Return reserved-but-unneeded pages to the admission ledger (e.g.
        the degradation band clamped max_tokens after the reservation)."""
        with self._lock:
            self._queued_kv_pages = max(0, self._queued_kv_pages - max(0, pages))

    def _service_s_locked(self) -> float:
        """Expected per-request service time: the per-token model (rate EMA x
        tokens-per-request EMA) once the engine has fed token counts, else
        the raw per-request EMA — see :meth:`note_service`."""
        if self._service_per_token_ema_s is not None:
            return self._service_per_token_ema_s * max(
                1.0, self._service_tokens_ema
            )
        return self._service_ema_s

    def _est_wait_s_locked(self, extra: int = 0, hist_q: Optional[float] = None) -> float:
        """Predicted time until a newly queued request could START.

        The depth*EMA/slots model is the rising-load term (a deepening queue
        pushes the prediction up immediately); ``hist_q`` — the warm
        queue-wait histogram quantile, computed by the caller outside the
        lock — floors it with the measured tail of realized waits, which the
        point EMA systematically underestimates under service-time variance."""
        model = (self._depth + extra) * self._service_s_locked() / self._slots
        if hist_q is not None and self._depth + extra > 0:
            return max(model, hist_q)
        return model

    def try_admit(
        self,
        priority: str = INTERACTIVE,
        deadline_s: Optional[float] = None,
        kv_pages: int = 0,
        *,
        now: Optional[float] = None,
    ) -> Admission:
        """The synchronous admission test (any thread).  On ``ok`` the caller
        MUST follow through with :meth:`enqueue` (depth — and the ``kv_pages``
        reservation — are charged here so a racing burst cannot overshoot
        either bound)."""
        cfg = self.cfg
        # the warm histogram quantile reads outside self._lock (its own lock)
        hist_q = self._hist_wait_q()
        with self._lock:
            self.submitted[priority] += 1
            # time until this request could START (everything ahead of it over
            # the engine's slots) — its own service time is the client's
            # business, the deadline test below only covers the queue wait.
            # The Retry-After hint IS that prediction (clamped): a client that
            # backs off exactly this long lands when a slot is expected free,
            # instead of the old est/2 guess (docs/AUTOSCALING.md).
            est = self._est_wait_s_locked(hist_q=hist_q)
            retry = min(30.0, max(0.2, est))
            if self._depth >= cfg.max_queue:
                self.shed["queue_full"] += 1
                return Admission(False, "queue_full", retry)
            if (
                kv_pages
                and self._kv_available is not None
                and self._kv_total
                and cfg.admit_max_wait_s is not None
            ):
                # projected KV pressure: queue depth alone cannot see a pool
                # exhausted by a few long-context admissions.  Shed only when
                # BOTH hold: the request could not start now (its worst-case
                # page demand exceeds the obtainable pages minus what the
                # queue already reserved), and its projected wait for pages —
                # the queued-KV backlog measured in full pool drains, each
                # costing ~one service time — exceeds the same estimated-wait
                # ceiling the depth test uses.  Same philosophy, distinct
                # reason (and counter) so operators can tell memory pressure
                # from compute backlog.
                avail = int(self._kv_available()) - self._queued_kv_pages
                kv_wait = (
                    (self._queued_kv_pages + kv_pages)
                    / self._kv_total
                    * self._service_s_locked()
                )
                if kv_pages > avail and kv_wait > cfg.admit_max_wait_s:
                    self.shed["kv_pressure"] += 1
                    return Admission(False, "kv_pressure", retry)
            if cfg.admit_max_wait_s is not None and est > cfg.admit_max_wait_s:
                self.shed["est_wait"] += 1
                return Admission(False, "estimated_wait", retry)
            if deadline_s is not None and est > deadline_s:
                # the queue alone would eat the whole deadline — shedding now
                # is kinder than a guaranteed DeadlineExceeded later
                self.shed["deadline_infeasible"] += 1
                return Admission(False, "deadline_infeasible", retry)
            self._depth += 1
            self._queued_kv_pages += max(0, int(kv_pages))
            clamp = None
            if (
                cfg.degrade_at < 1.0
                and self._depth >= cfg.degrade_at * cfg.max_queue
            ):
                clamp = int(cfg.degrade_max_tokens)
            if self._degrade_forced is not None:
                # autoscaler override: the tighter clamp wins
                clamp = (
                    self._degrade_forced
                    if clamp is None
                    else min(clamp, self._degrade_forced)
                )
            return Admission(True, clamp_max_tokens=clamp)

    def degraded(self) -> bool:
        """The degradation band is active — queue pressure past ``degrade_at``
        or the autoscaler's forced override — so the engine should skip
        speculative decoding (wasted verify forwards under load)."""
        cfg = self.cfg
        with self._lock:
            if self._degrade_forced is not None:
                return True
            return cfg.degrade_at < 1.0 and (
                self._depth >= cfg.degrade_at * cfg.max_queue
            )

    # ------------------------------------------------------------- the queue
    def _weight(self, key: Tuple[str, str]) -> float:
        cls_w = float(self.cfg.class_weights.get(key[0], 1.0))
        ten_w = float(self.cfg.tenant_weights.get(key[1], 1.0))
        return max(1e-6, cls_w * ten_w)

    def enqueue(self, req, *, front: bool = False) -> None:
        """Insert an (already admitted) request.  Requests that bypassed
        :meth:`try_admit` (internal/test paths writing the engine queue
        directly) are counted here so depth accounting stays true.

        ``front=True`` re-inserts at the HEAD of the request's (class, tenant)
        queue — the crash-only restart path (engine ``_restart``) uses it to
        re-submit salvaged in-flight work ahead of later arrivals.  The
        request keeps its class/tenant tags, so fair-share ordering across
        queues is untouched; within its own queue it simply resumes the place
        it already earned.  Depth was already released when the request was
        popped, so a ``front`` re-insert charges depth again (admitted flag
        notwithstanding) to keep the bound true."""
        key = (
            getattr(req, "priority", INTERACTIVE) or INTERACTIVE,
            getattr(req, "tenant", "default") or "default",
        )
        with self._lock:
            if front or not getattr(req, "admitted", False):
                self._depth += 1
                self._queued_kv_pages += max(0, getattr(req, "kv_pages", 0))
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = collections.deque()
            if not q:
                # an idle queue must not bank credit: restart at current vtime
                self._pass[key] = max(self._pass.get(key, 0.0), self._vtime)
            if front:
                q.appendleft(req)
            else:
                q.append(req)

    def _best_key_locked(self) -> Optional[Tuple[str, str]]:
        best = None
        for key, q in self._queues.items():
            if not q:
                continue
            cand = (self._pass[key], -self._weight(key), key)
            if best is None or cand < best[0]:
                best = (cand, key)
        return best[1] if best is not None else None

    def _reap_head_locked(self, now: float, expired: List):
        """Drop dead entries (cancelled / expired) from whichever queue is
        next up; returns the live (key, req) head or None when everything is
        empty.  Expired entries are APPENDED to ``expired``, not resolved —
        resolving a future runs its done-callbacks synchronously (the
        multi-replica router's re-dispatch takes other locks there), and
        doing that under ``self._lock`` is exactly the ABBA shape PR 7's
        review outlawed in :meth:`reap`/:meth:`drain`.  Callers resolve after
        releasing the lock (found by dabtlint DABT102)."""
        while True:
            key = self._best_key_locked()
            if key is None:
                return None
            q = self._queues[key]
            req = q[0]
            if req.future.cancelled():
                q.popleft()
                self._depth = max(0, self._depth - 1)
                self._release_kv_locked(req)
                self.cancelled_queued[key[0]] += 1
                continue
            dl = getattr(req, "deadline_at", None)
            if dl is not None and now >= dl:
                q.popleft()
                self._depth = max(0, self._depth - 1)
                self._release_kv_locked(req)
                self.expired_queued[key[0]] += 1
                expired.append(req)
                continue
            return key, req

    def _resolve_expired(self, expired: List, now: float) -> None:
        """Fail reaped entries OUTSIDE the lock (see _reap_head_locked)."""
        from .engine import _safe_resolve  # local import: engine imports us too

        for req in expired:
            _safe_resolve(
                req.future,
                exc=DeadlineExceeded(
                    f"deadline expired after {now - req.submitted_at:.2f}s in queue"
                ),
            )

    def peek(self, now: Optional[float] = None):
        """Next request the fair-share policy would run, without removing it
        (dead heads are reaped as a side effect)."""
        now = now if now is not None else self._clock()
        expired: List = []
        with self._lock:
            head = self._reap_head_locked(now, expired)
        self._resolve_expired(expired, now)
        return head[1] if head else None

    def pop(self, now: Optional[float] = None):
        """Remove and return the next request; charges its queue's virtual
        pass (this is the fair-share accounting step)."""
        now = now if now is not None else self._clock()
        expired: List = []
        with self._lock:
            head = self._reap_head_locked(now, expired)
            if head is not None:
                key, req = head
                self._queues[key].popleft()
                self._depth = max(0, self._depth - 1)
                self._release_kv_locked(req)
                self._vtime = self._pass[key]
                self._pass[key] += 1.0 / self._weight(key)
                self.admitted[key[0]] += 1
                self._waits[key[0]].append(now - req.submitted_at)
        self._resolve_expired(expired, now)
        return head[1] if head else None

    def reap(self, now: Optional[float] = None) -> int:
        """Drop cancelled/deadline-expired entries ANYWHERE in the queues
        (not just at pop time): the engine calls this every loop iteration so
        a queued request's DeadlineExceeded lands at ~its deadline even when
        every decode slot is busy — and the dead entry stops inflating depth
        (which would shed admittable work with spurious queue_full 429s).
        Returns the number of entries dropped."""
        from .engine import _safe_resolve

        now = now if now is not None else self._clock()
        dropped = 0
        expired = []
        with self._lock:
            for key, q in self._queues.items():
                if not q:
                    continue
                keep: Deque = collections.deque()
                while q:
                    req = q.popleft()
                    if req.future.cancelled():
                        self._depth = max(0, self._depth - 1)
                        self._release_kv_locked(req)
                        self.cancelled_queued[key[0]] += 1
                        dropped += 1
                        continue
                    dl = getattr(req, "deadline_at", None)
                    if dl is not None and now >= dl:
                        self._depth = max(0, self._depth - 1)
                        self._release_kv_locked(req)
                        self.expired_queued[key[0]] += 1
                        dropped += 1
                        expired.append(req)
                        continue
                    keep.append(req)
                q.extend(keep)
        # resolve OUTSIDE the lock: done-callbacks (the multi-replica
        # router's re-dispatch) may take other schedulers' locks
        for req in expired:
            _safe_resolve(
                req.future,
                exc=DeadlineExceeded(
                    f"deadline expired after "
                    f"{now - req.submitted_at:.2f}s in queue"
                ),
            )
        return dropped

    def drain(self, err: BaseException) -> None:
        """Fail everything still queued (engine shutdown).

        Futures resolve OUTSIDE the lock: a routed request's done-callback
        re-dispatches to ANOTHER replica — taking that replica's scheduler
        lock — and two replicas dying simultaneously would otherwise hold
        each other's locks in an ABBA deadlock (each engine thread draining
        its own scheduler while re-dispatching into the other's)."""
        from .engine import _safe_resolve

        victims = []
        with self._lock:
            for q in self._queues.values():
                while q:
                    victims.append(q.popleft())
                    self._depth = max(0, self._depth - 1)
            self._depth = max(0, self._depth)
            self._queued_kv_pages = 0
        for req in victims:
            _safe_resolve(req.future, exc=err)

    def _release_kv_locked(self, req) -> None:
        self._queued_kv_pages = max(
            0, self._queued_kv_pages - max(0, getattr(req, "kv_pages", 0))
        )

    # ------------------------------------------------------------- telemetry
    def note_service(self, seconds: float, tokens: int = 0) -> None:
        """Fold one finished request's service time into the EMA driving the
        estimated-wait admission test.

        With ``tokens > 0`` (the decode steps the request's slot actually sat
        through — the engine charges fused N-step ticks their full N even
        when EOS lands mid-tick, plus one unit per chunked-prefill dispatch,
        sequential or piggybacked), the model becomes PER-TOKEN: a per-token
        rate EMA and a tokens-per-request EMA whose product replaces the raw
        per-request EMA in :meth:`_est_wait_s_locked`.  Why: a
        ``decode_steps=N`` engine delivers residency in N-step quanta and the
        host sees finishes ``lookahead`` ticks late, so short requests'
        measured residency inflates by up to ``lookahead * (N-1)`` steps —
        feeding that directly into the per-request EMA inflates every
        predicted queue wait (and therefore 429 Retry-After hints and the
        autoscaler's backlog signal).  Normalizing by the steps the slot
        really occupied keeps the rate honest; the tokens EMA restores the
        per-request scale.  Calls without ``tokens`` keep the legacy
        per-request EMA behavior byte-for-byte (and that EMA keeps updating
        regardless, as the cold-start fallback)."""
        a = self.cfg.service_time_alpha
        with self._lock:
            self._service_ema_s = (1 - a) * self._service_ema_s + a * max(
                0.0, float(seconds)
            )
            if tokens > 0:
                per_tok = max(0.0, float(seconds)) / int(tokens)
                if self._service_per_token_ema_s is None:
                    self._service_per_token_ema_s = per_tok
                    self._service_tokens_ema = float(tokens)
                else:
                    self._service_per_token_ema_s = (
                        (1 - a) * self._service_per_token_ema_s + a * per_tok
                    )
                    self._service_tokens_ema = (
                        (1 - a) * self._service_tokens_ema + a * float(tokens)
                    )
            elif self._service_per_token_ema_s is not None:
                # token-less evidence after the model warmed (a test harness
                # or non-engine caller): fold it in at the learned
                # tokens-per-request so it still moves the effective model —
                # the tokens EMA itself carries no new information here
                per_tok = max(0.0, float(seconds)) / max(
                    1.0, self._service_tokens_ema
                )
                self._service_per_token_ema_s = (
                    (1 - a) * self._service_per_token_ema_s + a * per_tok
                )

    def note_expired_running(self, priority: str) -> None:
        with self._lock:
            self.expired_running[priority or INTERACTIVE] += 1

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return self._depth

    def pressure(self) -> float:
        with self._lock:
            return self._depth / max(1, self.cfg.max_queue)

    def est_wait_s(self) -> float:
        hist_q = self._hist_wait_q()
        with self._lock:
            return self._est_wait_s_locked(hist_q=hist_q)

    @staticmethod
    def _pctl(sorted_vals, frac: float) -> float:
        if not sorted_vals:
            return 0.0
        idx = min(len(sorted_vals) - 1, max(0, round(frac * (len(sorted_vals) - 1))))
        return sorted_vals[idx]

    def wait_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-class queue-wait percentiles (ms) over the sample window."""
        with self._lock:
            out = {}
            for cls, samples in self._waits.items():
                vals = sorted(samples)
                out[cls] = {
                    "n": len(vals),
                    "p50_ms": round(self._pctl(vals, 0.50) * 1e3, 2),
                    "p95_ms": round(self._pctl(vals, 0.95) * 1e3, 2),
                }
            return out

    def stats(self) -> dict:
        """One JSON-able snapshot for /healthz and tick_stats."""
        waits = self.wait_stats()
        # the engine-side gauges run OUTSIDE the lock: they read engine/tier
        # state (controller verdict, host-tier ledger) and must not nest locks
        spec = self._spec_gauge_fn() if self._spec_gauge_fn is not None else None
        kv_tier = (
            self._kv_tier_stats_fn() if self._kv_tier_stats_fn is not None else None
        )
        hist_q = self._hist_wait_q()
        with self._lock:
            return {
                "queue_depth": self._depth,
                "queued_kv_pages": self._queued_kv_pages,
                "max_queue": self.cfg.max_queue,
                "pressure": round(self._depth / max(1, self.cfg.max_queue), 4),
                "est_wait_s": round(self._est_wait_s_locked(hist_q=hist_q), 4),
                "est_wait_source": "histogram" if hist_q is not None else "ema",
                "wait_hist_q_s": round(hist_q, 4) if hist_q is not None else None,
                "service_ema_s": round(self._service_ema_s, 4),
                # the per-token model actually driving est_wait once warm
                # (None until the engine feeds token counts): rate x
                # tokens-per-request — see note_service
                "service_model_s": round(self._service_s_locked(), 4),
                "service_per_token_ema_ms": (
                    round(self._service_per_token_ema_s * 1e3, 4)
                    if self._service_per_token_ema_s is not None
                    else None
                ),
                "service_tokens_ema": round(self._service_tokens_ema, 2),
                "degraded": self._degrade_forced is not None
                or (
                    self.cfg.degrade_at < 1.0
                    and self._depth >= self.cfg.degrade_at * self.cfg.max_queue
                ),
                "degrade_forced": self._degrade_forced is not None,
                "submitted": dict(self.submitted),
                "admitted": dict(self.admitted),
                "shed": dict(self.shed),
                "expired_queued": dict(self.expired_queued),
                "expired_running": dict(self.expired_running),
                "cancelled_queued": dict(self.cancelled_queued),
                "wait": waits,
                **({"spec_disabled": spec} if spec is not None else {}),
                **({"kv_tier": kv_tier} if kv_tier is not None else {}),
            }
