"""Fault-tolerant multi-replica serving: the front-door engine router.

The reference's only hardware-facing component is a single FastAPI gpu_service
process — one crash takes down every bot (PAPER.md §7) — and until now this
repo's serving plane was likewise ONE :class:`~.engine.GenerationEngine`:
supervised (crash-only restarts, a restart circuit — docs/RESILIENCE.md) but
with no redundancy.  :class:`EngineRouter` owns N engine replicas — each
independently supervised, with its own scheduler, KV page pool, and fault
injector — and fronts them with the engine's own ``submit()`` /
``generate()`` / ``generate_stream()`` surface, so the HTTP layer and the
providers cannot tell a fleet from a single engine.

Dispatch policy (docs/RESILIENCE.md "Fleet topology"):

- **Health first.**  A replica is a candidate only when it is not draining,
  its engine loop is alive (running thread, fresh heartbeat, restart circuit
  closed), and its per-replica :class:`~...ai.providers.failover.CircuitBreaker`
  admits it.  The breaker — reused verbatim from the provider failover plane —
  is fed by :class:`~.engine.EngineUnavailable`, heartbeat staleness, dead
  threads, and replica-shaped request failures; a half-open breaker admits
  exactly one probe request, so a recovering replica earns traffic back one
  request at a time instead of eating a thundering herd.
- **Prefix affinity, then least-loaded.**  A request carrying a shareable
  prefix (system prompt + packed RAG context) is routed to the replica whose
  KV page pool *already holds* that prefix — a read-only, LRU-neutral registry
  peek (:meth:`~.kv_pool.PageAllocator.holds_prefix`), so multi-turn dialogs
  keep hitting the prefix cache they warmed instead of re-prefilling on a
  random replica.  Everything else (and affinity misses) goes least-loaded:
  ``queued_depth + num_active``, rotation tie-break.  Health and breaker state
  take precedence over affinity — a cached prefix is never a reason to route
  into a sick replica.
- **Token-less re-route.**  When a replica fails a request that has emitted
  NO tokens (replica died with the request queued or mid-prefill, engine
  degraded, crash-only restart budget exhausted), the router re-submits it to
  another healthy replica — bounded by the same ``max_request_restarts``
  budget the engine's own crash-restart salvage uses, so a request that
  deterministically kills engines cannot hop forever.  Requests past their
  first token fail cleanly (a replay would double-bill latency or repeat
  streamed output) — exactly the single-engine restart contract, lifted to
  the fleet.
- **Graceful drain.**  :meth:`drain` stops admitting to one replica, lets its
  in-flight work finish (deadline-bounded, injectable clock so tests are
  deterministic), then restarts it while the rest of the fleet absorbs
  traffic; :meth:`rolling_restart` chains that over every replica for
  zero-downtime restarts.  ``drain_all`` (no restart) is the SIGTERM path:
  the server stops admission, the fleet finishes what it accepted, the
  process exits 0.

- **Dynamic fleet size.**  :meth:`add_replica` spawns a fresh replica from
  the registry-provided factory (same shared weights, its own scheduler/KV
  pool/faults) and :meth:`remove_replica` drains one and detaches it — the
  SLO autoscaler's actuators (serving/autoscaler.py, docs/AUTOSCALING.md).
  Dispatch state is held by replica OBJECT, never by index, so a request's
  re-route callback stays correct while the fleet grows or shrinks under it.

Chaos sites ``replica_dead`` / ``replica_slow`` (serving/faults.py) exercise
all of the above deterministically: ``replica_dead`` kills the replica the
dispatcher is about to pick — in-flight work fails, the breaker trips, and
token-less requests re-route — and the ``router_*`` bench section measures
goodput and recovery the same way ``chaos_*`` does for one engine.
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Set

from ..ai.providers.failover import CircuitBreaker
from .engine import EngineUnavailable, GenerationEngine, _safe_resolve
from .kv_pool import TIER_DISK, TIER_HBM, TIER_HOST
from .obs import new_trace_id
from .scheduler import SchedulerRejected

logger = logging.getLogger(__name__)


class FleetPrefixRegistry:
    """Router-owned map of which replica holds which warm prefix, at which
    tier — the fleet-level promotion of the per-replica ``holds_prefix`` peek
    (docs/KV_PAGING.md "Tiered KV").

    Fed by the engines' tier-transition events (register/spill/restore/
    evict — :meth:`GenerationEngine.set_prefix_listener`), so it SURVIVES
    what the per-replica peek cannot: a crash-only restart downgrades a
    replica's entries from ``hbm`` to ``host`` (write-through kept the
    bytes) instead of forgetting them, and a scale-down migration re-points
    entries at the absorbing replica.  Affinity dispatch reads
    :meth:`holders` instead of peeking N allocators per request.

    Lock discipline: one leaf lock.  Event callbacks arrive from engine
    threads (and the router thread during migration absorb) OUTSIDE every
    engine/allocator/tier lock; readers are dispatch and stats threads.
    Nothing is called out of this class while the lock is held."""

    # event -> (tier, present-after-event)
    _EVENTS = {
        "register": (TIER_HBM, True),
        "restore": (TIER_HBM, True),  # re-registered by the restore admit
        "evict_spilled": (TIER_HBM, False),
        "evict_dropped": (TIER_HBM, False),
        "host_put": (TIER_HOST, True),
        "disk_promote": (TIER_HOST, True),
        "host_evict_disk": (TIER_HOST, False),
        "host_evict_dropped": (TIER_HOST, False),
        "host_put_too_large": (TIER_HOST, False),
        "disk_drop": (TIER_DISK, False),
    }
    # host_evict_disk also ADDS the disk tier; disk_promote removes it
    _RANK = {TIER_HBM: 0, TIER_HOST: 1, TIER_DISK: 2}

    def __init__(self):
        self._lock = threading.Lock()
        # key -> {replica_name -> set(tiers)}
        self._entries: dict = {}
        # first token -> set(keys): holders() only scans keys that can
        # possibly prefix the prompt, so per-dispatch cost tracks the
        # MATCHING warm set, not total fleet warm state
        self._by_first: dict = {}

    def _index_add_locked(self, key: tuple) -> None:
        self._by_first.setdefault(key[0], set()).add(key)

    def _index_drop_locked(self, key: tuple) -> None:
        bucket = self._by_first.get(key[0])
        if bucket is not None:
            bucket.discard(key)
            if not bucket:
                del self._by_first[key[0]]

    def on_event(self, replica: str, event: str, key: tuple, length: int) -> None:
        tier_change = self._EVENTS.get(event)
        if tier_change is None:
            return
        tier, present = tier_change
        with self._lock:
            holders = self._entries.setdefault(key, {})
            self._index_add_locked(key)
            tiers = holders.setdefault(replica, set())
            if present:
                tiers.add(tier)
            else:
                tiers.discard(tier)
            if event == "host_evict_disk":
                tiers.add(TIER_DISK)
            elif event == "disk_promote":
                tiers.discard(TIER_DISK)
            if not tiers:
                holders.pop(replica, None)
            if not holders:
                self._entries.pop(key, None)
                self._index_drop_locked(key)

    def apply_holding(
        self, replica: str, key: tuple, length: int, tier: str
    ) -> None:
        """Directly assert one (replica, key, tier) holding — the fleet
        plane's SNAPSHOT application path (serving/fleet.py): when a peer's
        gossip delta log has been trimmed past the follower's cursor, the
        follower drops that peer's holdings and re-applies the full holdings
        snapshot through here instead of replaying events it never saw."""
        if tier not in self._RANK or length <= 0:
            return
        with self._lock:
            holders = self._entries.setdefault(key, {})
            self._index_add_locked(key)
            holders.setdefault(replica, set()).add(tier)

    def drop_replica(self, replica: str) -> int:
        """Forget every entry held only by ``replica`` (detach epilogue —
        migrated entries were already re-pointed by the target's absorb
        events).  Returns how many (key, replica) holdings dropped."""
        n = 0
        with self._lock:
            for key in list(self._entries):
                holders = self._entries[key]
                if replica in holders:
                    del holders[replica]
                    n += 1
                    if not holders:
                        del self._entries[key]
                        self._index_drop_locked(key)
        return n

    def holders(
        self, prompt_ids: Sequence[int], prefix_len: int
    ) -> Dict[str, str]:
        """replica name -> best tier (``hbm`` < ``host`` < ``disk``) over
        EVERY registered prefix of this prompt that replica holds — not just
        the fleet-wide longest match.  Per-replica aggregation preserves the
        old peek-every-allocator semantics: when the longest-prefix holder
        is draining or unhealthy, a replica warm with a SHORTER prefix (an
        earlier turn of the same session) still beats a cold one."""
        if prefix_len <= 0:
            return {}
        n = len(prompt_ids)
        if n == 0:
            return {}
        first = prompt_ids[0]
        out: Dict[str, str] = {}
        with self._lock:
            # first-token bucket + O(1) last-token rejection before the
            # O(ln) slice: this runs under the dispatch lock on EVERY
            # routed request, so cost tracks the matching warm set, not
            # total fleet warm state
            for key in self._by_first.get(first, ()):
                holders = self._entries.get(key)
                if holders is None:
                    continue
                ln = len(key)
                if (
                    ln >= n
                    or key[-1] != prompt_ids[ln - 1]
                    or tuple(prompt_ids[:ln]) != key
                ):
                    continue
                for rep, tiers in holders.items():
                    if not tiers:
                        continue
                    tier = min(tiers, key=self._RANK.__getitem__)
                    cur = out.get(rep)
                    if cur is None or self._RANK[tier] < self._RANK[cur]:
                        out[rep] = tier
        return out

    def stats(self) -> dict:
        with self._lock:
            per_tier = {TIER_HBM: 0, TIER_HOST: 0, TIER_DISK: 0}
            holdings = 0
            for holders in self._entries.values():
                for tiers in holders.values():
                    holdings += 1
                    for t in tiers:
                        per_tier[t] += 1
            return {
                "prefixes": len(self._entries),
                "holdings": holdings,
                "hbm": per_tier[TIER_HBM],
                "host": per_tier[TIER_HOST],
                "disk": per_tier[TIER_DISK],
            }


class _StreamShim:
    """Router-side token tap between an engine and the client's TokenStream.

    Counts every client-visible token (the re-route eligibility test: ONLY
    token-less requests may move replica) and forwards to the real stream
    when one is attached.  The terminal event is NOT forwarded from the inner
    engine future — the router resolves its OUTER future (which carries the
    client stream's ``finish`` callback) only once re-routing is settled, so
    a replica death mid-queue never closes the client stream early."""

    __slots__ = ("inner", "tokens")

    def __init__(self, inner: Any = None):
        self.inner = inner
        self.tokens = 0

    def push_token(self, tok: int, *, notify: bool = True) -> bool:
        self.tokens += 1
        if self.inner is not None:
            return self.inner.push_token(tok, notify=notify)
        return False

    def notify_now(self) -> None:
        if self.inner is not None:
            self.inner.notify_now()

    def finish(self, fut: Future) -> None:  # inner future done-callback
        pass  # terminal rides the router's outer future instead


class _Replica:
    """One engine behind the router: breaker, drain flag, counters."""

    __slots__ = (
        "engine",
        "name",
        "breaker",
        "draining",
        "dispatched",
        "completed_ok",
        "last_success_at",
    )

    def __init__(self, engine: GenerationEngine, name: str, breaker: CircuitBreaker):
        self.engine = engine
        self.name = name
        self.breaker = breaker
        self.draining = False
        self.dispatched = 0
        self.completed_ok = 0
        self.last_success_at: Optional[float] = None


class _Routed:
    """Mutable per-request routing state carried across re-dispatches."""

    __slots__ = (
        "prompt_ids",
        "kwargs",
        "outer",
        "shim",
        "reroutes",
        "replica",
        "inner",
        "holders",
        "deadline_at",
    )

    def __init__(
        self,
        prompt_ids: List[int],
        kwargs: dict,
        outer: Future,
        shim: _StreamShim,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.prompt_ids = prompt_ids
        self.kwargs = kwargs
        self.outer = outer
        self.shim = shim
        self.reroutes = 0
        # the _Replica OBJECT currently carrying the request — never an index:
        # add_replica/remove_replica shift list positions under live requests
        self.replica: Optional[_Replica] = None
        self.inner: Optional[Future] = None
        # the client's ABSOLUTE deadline, fixed at first submission: each
        # engine.submit computes its own deadline_at from deadline_s, so a
        # re-route must pass the REMAINING budget, not restart the clock —
        # otherwise every hop silently grants the client a fresh deadline.
        # The router's injectable clock rides in so fake-time drain tests
        # see deadline math too (dabtlint DABT105).
        self.deadline_at: Optional[float] = None
        if kwargs.get("deadline_s") is not None:
            self.deadline_at = clock() + float(kwargs["deadline_s"])
        # replicas whose prefix registry held this prompt's prefix at the
        # last candidate ordering — a hit is counted only when the replica
        # ACTUALLY dispatched to is one of them (a skipped holder is a miss)
        self.holders: Set["_Replica"] = set()


class EngineRouter:
    """N supervised :class:`~.engine.GenerationEngine` replicas behind one
    engine-shaped face (``submit``/``generate``/``generate_stream``/stats).

    ``clock``/``sleep`` are injectable so the drain deadline logic is
    deterministic under test; the engines themselves keep real time."""

    def __init__(
        self,
        engines: Sequence[GenerationEngine],
        *,
        names: Optional[Sequence[str]] = None,
        breaker_threshold: int = 3,
        breaker_reset_s: float = 10.0,
        max_reroutes: Optional[int] = None,
        faults=None,
        replica_factory: Optional[Callable[[int], GenerationEngine]] = None,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if not engines:
            raise ValueError("EngineRouter needs at least one engine replica")
        self._clock = clock
        self._sleep = sleep
        self._faults = faults
        # spawns replica N from the shared ModelSpec weights (registry
        # closure) — the autoscaler's scale-up actuator; None = fixed fleet
        self._replica_factory = replica_factory
        self._breaker_threshold = int(breaker_threshold)
        self._breaker_reset_s = float(breaker_reset_s)
        names = list(names) if names else [f"replica{i}" for i in range(len(engines))]
        if len(names) != len(engines):
            raise ValueError("names must match engines 1:1")
        self.replicas: List[_Replica] = [
            _Replica(
                eng,
                name,
                CircuitBreaker(breaker_threshold, breaker_reset_s, clock=clock),
            )
            for eng, name in zip(engines, names)
        ]
        # monotonic spawn counter: replica names are never reused, so flight
        # artifacts and /metrics labels stay unambiguous across scale cycles
        self._spawned = len(engines)
        # mesh-sliced fleet (parallel/slicing.py): the registry attaches its
        # MeshPlanner here so /healthz + /metrics can report slice capacity
        # next to the fleet gauges; None on an unsliced fleet
        self.mesh_planner = None
        # one request survives at most this many replica hops — the same
        # budget the engines' own crash-restart salvage enforces per replica
        self.max_reroutes = (
            int(max_reroutes)
            if max_reroutes is not None
            else max(e.max_request_restarts for e in engines)
        )
        self.tokenizer = engines[0].tokenizer
        # the fleet's context contract is the tightest replica's (the
        # in-process TPUProvider reads this off whatever the registry hands
        # it for prompt budgeting — replicas are homogeneous today, but min
        # stays honest if that ever changes)
        self.max_seq_len = min(e.max_seq_len for e in engines)
        self.scheduler = None  # per-replica schedulers; see router_stats()
        self._lock = threading.Lock()
        self._rr = 0  # rotation counter: load-tie break spreads, not pins
        self.affinity_hits = 0
        self.affinity_misses = 0
        self.reroutes = 0
        self.rerouted_failed = 0  # token-less re-routable failures past budget
        # replica-shaped failures a request could NOT be re-routed away from
        # (it was past its first client-visible token — the honest cost of a
        # replica death, distinguished from token-less goodput in the bench)
        self.failed_past_first_token = 0
        self.drains = 0
        self.drain_shed = 0  # requests failed by a deadline-forced drain
        self.no_replica_available = 0
        # dynamic-fleet counters (scale events are scrapeable via /metrics)
        self.replicas_added = 0
        self.replicas_removed = 0
        self.replica_restarts = 0
        # --- durable warm state (docs/KV_PAGING.md "Tiered KV") -----------
        # fleet-wide prefix registry: which replica holds which warm prefix,
        # at which tier — affinity survives drains, restarts, scale-downs
        self.prefix_registry = FleetPrefixRegistry()
        # scale-down warm-state accounting: pages the fleet LOST at a
        # detach (the satellite counter — visible even before migration
        # lands a target) vs pages/entries migration preserved
        self.pages_lost_at_detach = 0
        self.pages_migrated = 0
        self.entries_migrated = 0
        self.detach_migrations = 0
        # cross-process fleet plane tap (set_event_tap): forwarded a copy of
        # every tier event so the gossip delta log sees what the registry saw
        self._event_tap: Optional[Callable[..., None]] = None
        for rep in self.replicas:
            self._wire_replica(rep)

    def _wire_replica(self, rep: "_Replica") -> None:
        """Subscribe the fleet prefix registry to this replica's KV
        tier-transition events (no-op for engines without the hook — stub
        engines in tests).  When an event tap is attached
        (:meth:`set_event_tap` — the cross-process fleet plane's gossip
        log), every event ALSO forwards there after the registry update."""
        setter = getattr(rep.engine, "set_prefix_listener", None)
        if callable(setter):
            name = rep.name

            def _listener(event, key, length, pages, _n=name):
                self.prefix_registry.on_event(_n, event, key, length)
                tap = self._event_tap
                if tap is not None:
                    try:
                        tap(_n, event, key, length)
                    except Exception:
                        logger.exception("router event tap failed (%s)", event)

            setter(_listener)

    def set_event_tap(self, fn: Optional[Callable[..., None]]) -> None:
        """Attach ``fn(replica, event, key, length)`` to ride every KV
        tier-transition event AFTER the local prefix-registry update — how
        the cross-process fleet plane (serving/fleet.py) builds its gossip
        delta log without stealing the engines' single prefix listener."""
        self._event_tap = fn

    # engine.generate / generate_stream only touch self.tokenizer and
    # self.submit — both present here, so the router reuses them verbatim
    # (tokenization, prefix split, stream plumbing identical to one engine)
    generate = GenerationEngine.generate
    generate_stream = GenerationEngine.generate_stream

    # ------------------------------------------------------------- dispatch
    def _healthy(self, rep: _Replica) -> bool:
        """Dispatch-time liveness — the ENGINE's own predicate (the same one
        /healthz reports), so routing and health reporting can never
        disagree.  (The breaker is consulted separately — this is the direct
        evidence that also FEEDS it when stale.)"""
        return rep.engine.healthy()

    def _load(self, rep: _Replica) -> int:
        return rep.engine.queued_depth() + rep.engine.num_active

    def _candidate_order(
        self, state: _Routed, exclude: Optional[Set["_Replica"]]
    ) -> List["_Replica"]:
        """Dispatch preference: non-draining replicas, prefix-registry holders
        first (least-loaded among holders), then everything else least-loaded
        with a rotating tie-break.  Returns replica OBJECTS over a snapshot of
        the (possibly growing/shrinking) fleet — positions are only used for
        the rotation tie-break."""
        with self._lock:
            self._rr += 1
            rr = self._rr
            reps = list(self.replicas)
        n = max(1, len(reps))
        pos = {id(rep): i for i, rep in enumerate(reps)}
        cands = [
            rep
            for rep in reps
            if not rep.draining and (not exclude or rep not in exclude)
        ]
        cands.sort(key=lambda rep: (self._load(rep), (pos[id(rep)] - rr) % n))
        prefix_len = state.kwargs.get("prefix_len", 0)
        state.holders = set()
        if prefix_len and len(cands) > 1:
            # the fleet registry answers in one lookup (and knows the TIER:
            # an HBM holder beats a host/disk holder — zero-copy sharing vs
            # a restore upload); the per-replica peek remains as a fallback
            # for engines that emit no tier events (legacy layout, stubs)
            tiers = self.prefix_registry.holders(state.prompt_ids, prefix_len)
            hbm = [rep for rep in cands if tiers.get(rep.name) == TIER_HBM]
            warm = [
                rep
                for rep in cands
                if tiers.get(rep.name) in (TIER_HOST, TIER_DISK)
            ]
            # peek every candidate the registry has NO answer for — not
            # just the all-empty case: a non-event-emitting replica's warm
            # state must stay visible even while event-emitting replicas
            # hold (worse-tier) matches of the same session
            for rep in cands:
                if rep.name not in tiers and rep.engine.holds_prefix(
                    state.prompt_ids, prefix_len
                ):
                    hbm.append(rep)
            if hbm or warm:
                state.holders = set(hbm) | set(warm)
                rest = [rep for rep in cands if rep not in state.holders]
                cands = hbm + warm + rest
        return cands

    def submit(
        self,
        prompt_ids: Sequence[int],
        *,
        max_tokens: int = 1024,
        temperature: float = 0.8,
        top_p: float = 0.95,
        json_format: bool = False,
        prefix_len: int = 0,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
        stream: Any = None,
        trace_id: Optional[str] = None,
    ) -> Future:
        """Thread-safe fleet submission; returns Future[GenerationResult].

        Raises :class:`SchedulerRejected` when every candidate replica sheds
        (fleet-wide overload) and :class:`EngineUnavailable` when no healthy
        replica exists — the same synchronous contract one engine has, so the
        HTTP layer's 429/503 mapping applies unchanged."""
        if self._faults is not None:
            # deterministic fleet chaos: a stalled dispatch hop, or the
            # picked replica dying under the dispatcher's feet (the injected
            # sleep, so fake-time harnesses stay deterministic)
            delay = self._faults.sleep_s("replica_slow")
            if delay:
                self._sleep(delay)
        outer: Future = Future()
        if stream is not None:
            outer.add_done_callback(stream.finish)
        state = _Routed(
            list(prompt_ids),
            dict(
                max_tokens=max_tokens,
                temperature=temperature,
                top_p=top_p,
                json_format=json_format,
                prefix_len=prefix_len,
                priority=priority,
                tenant=tenant,
                deadline_s=deadline_s,
                # assigned HERE (not per-engine) so every re-route hop and
                # the flight-recorder events of each replica carry ONE id —
                # a failed leg and its retry correlate by trace_id alone
                trace_id=trace_id or new_trace_id(),
            ),
            outer,
            _StreamShim(stream),
            clock=self._clock,
        )
        if self._faults is not None and self._faults.should_fire("replica_dead"):
            order = self._candidate_order(state, None)
            if order:
                self._kill(order[0])
        self._dispatch(state, exclude=None, sync=True)
        # outer cancel (client disconnect) must reach whichever inner future
        # currently carries the request so the engine's reap frees the slot
        outer.add_done_callback(lambda f: self._propagate_cancel(state, f))
        return outer

    def _propagate_cancel(self, state: _Routed, outer: Future) -> None:
        if outer.cancelled():
            inner = state.inner
            if inner is not None and not inner.done():
                inner.cancel()

    def _dispatch(
        self, state: _Routed, exclude: Optional[Set["_Replica"]], *, sync: bool
    ) -> None:
        """Try candidates in preference order; on ``sync`` (the caller's
        thread) synchronous rejections raise, on re-route they resolve the
        outer future instead."""
        last_unavail: Optional[EngineUnavailable] = None
        last_shed: Optional[SchedulerRejected] = None
        for rep in self._candidate_order(state, exclude):
            br = rep.breaker
            if not br.allow():
                continue
            if not self._healthy(rep):
                # heartbeat-stale / dead-thread / degraded evidence feeds the
                # breaker directly (and clears any probe slot allow() claimed)
                br.record_failure()
                continue
            try:
                inner = rep.engine.submit(state.prompt_ids, **state.kwargs, stream=state.shim)
            except EngineUnavailable as e:
                br.record_failure()
                last_unavail = e
                continue
            except SchedulerRejected as e:
                # load shed is pressure, not a fault: the probe slot frees
                # and the breaker's failure streak is untouched
                br.release_probe()
                last_shed = e
                continue
            with self._lock:
                rep.dispatched += 1
                if state.kwargs.get("prefix_len", 0) and len(self.replicas) > 1:
                    # a hit only if THIS replica holds the prefix — a holder
                    # skipped for health/breaker reasons is a miss (the
                    # request re-prefills), and the gauge must say so
                    if rep in state.holders:
                        self.affinity_hits += 1
                    else:
                        self.affinity_misses += 1
            state.replica = rep
            state.inner = inner
            if state.outer.cancelled():
                inner.cancel()
            inner.add_done_callback(
                lambda f, s=state, r=rep: self._on_inner_done(s, r, f)
            )
            return
        # no replica took it
        with self._lock:
            self.no_replica_available += 1
            reps = list(self.replicas)
        exc: BaseException
        if last_shed is not None and last_unavail is None:
            exc = last_shed
        elif last_unavail is not None and last_shed is None:
            exc = last_unavail
        elif last_shed is not None and last_unavail is not None:
            # mixed: prefer the shed (429 + honest Retry-After) — part of
            # the fleet is alive, the client should back off and retry
            exc = last_shed
        else:
            # honest Retry-After: the soonest any breaker would re-admit —
            # the predictive-admission discipline (no fixed constants) applied
            # to the 503 path too (docs/AUTOSCALING.md)
            hints = [rep.breaker.retry_in_s() for rep in reps]
            retry = min((h for h in hints if h > 0), default=1.0)
            exc = EngineUnavailable(
                "no healthy replica available",
                retry_after_s=min(30.0, max(0.5, retry)),
            )
        if sync:
            raise exc
        _safe_resolve(state.outer, exc=exc)

    @staticmethod
    def _reroutable(exc: BaseException) -> bool:
        """Replica-shaped failures (the replica died / degraded / kept
        crashing) re-route; request-shaped outcomes (deadline, shed,
        poisoned prompt, bad arguments) stick with the request."""
        from .engine import RequestPoisoned
        from .scheduler import DeadlineExceeded

        if isinstance(
            exc, (DeadlineExceeded, SchedulerRejected, RequestPoisoned, ValueError)
        ):
            return False
        return isinstance(exc, Exception)

    def _on_inner_done(self, state: _Routed, rep: "_Replica", inner: Future) -> None:
        br = rep.breaker
        if state.outer.cancelled():
            # the client went away; the engine's reap already owns cleanup —
            # just free any half-open probe slot this request held
            br.release_probe()
            return
        if inner.cancelled():
            br.release_probe()
            state.outer.cancel()
            return
        exc = inner.exception()
        if exc is None:
            now = self._clock()
            with self._lock:
                rep.completed_ok += 1
                rep.last_success_at = now
            br.record_success()
            _safe_resolve(state.outer, result=inner.result())
            return
        if self._reroutable(exc):
            br.record_failure()
            if state.shim.tokens == 0 and state.reroutes < self.max_reroutes:
                if state.deadline_at is not None:
                    # the single-engine salvage keeps the original
                    # _Request.deadline_at; the fleet contract must match —
                    # pass the REMAINING budget, and a hop with none left is
                    # a deadline failure, not a fresh attempt
                    remaining = state.deadline_at - self._clock()
                    if remaining <= 0:
                        from .scheduler import DeadlineExceeded

                        _safe_resolve(
                            state.outer,
                            exc=DeadlineExceeded(
                                "deadline expired while re-routing off a "
                                f"failed replica ({rep.name})"
                            ),
                        )
                        return
                    state.kwargs["deadline_s"] = remaining
                state.reroutes += 1
                with self._lock:
                    self.reroutes += 1
                obs = getattr(rep.engine, "obs", None)
                if obs is not None:
                    # the failed replica's flight ring keeps the hop evidence
                    # (a later dump of EITHER replica shows the re-route)
                    obs.flight.record(
                        "reroute",
                        trace_id=state.kwargs.get("trace_id"),
                        from_replica=rep.name,
                        hop=state.reroutes,
                        error=f"{type(exc).__name__}: {exc}",
                    )
                logger.warning(
                    "router: re-routing token-less request off %s (%s: %s); "
                    "hop %d/%d",
                    rep.name,
                    type(exc).__name__,
                    exc,
                    state.reroutes,
                    self.max_reroutes,
                )
                try:
                    self._dispatch(state, exclude={rep}, sync=False)
                except Exception as redispatch_exc:  # pragma: no cover - belt
                    # an unexpected submit error here would otherwise be
                    # swallowed by Future._invoke_callbacks and leave the
                    # outer future pending FOREVER — resolve it instead
                    logger.exception("router: re-dispatch failed")
                    _safe_resolve(state.outer, exc=redispatch_exc)
                return
            with self._lock:
                if state.shim.tokens == 0:
                    self.rerouted_failed += 1
                else:
                    self.failed_past_first_token += 1
        else:
            # the replica answered (with a request-level outcome): that
            # resolves a half-open probe as success and ends any streak
            br.record_success()
        _safe_resolve(state.outer, exc=exc)

    # ------------------------------------------------------ chaos / recovery
    def _kill(self, rep: "_Replica") -> None:
        logger.warning("router: chaos killed %s", rep.name)
        obs = getattr(rep.engine, "obs", None)
        if obs is not None:
            obs.flight.record("replica_kill", replica=rep.name)
        rep.engine._running = False

    def kill_replica(self, idx: int) -> None:
        """Abrupt replica death (the ``replica_dead`` chaos site): drop the
        engine's run flag so its loop exits at the top of the next iteration
        and its ``_shutdown`` fails everything in flight — exactly what the
        router must survive.  No drain, no goodbye."""
        self._kill(self.replicas[idx])

    def _restart_rep(self, rep: "_Replica", *, stop_timeout_s: float = 30.0) -> None:
        rep.engine.stop(drain_timeout_s=stop_timeout_s)
        rep.engine.start()
        rep.breaker.record_success()
        with self._lock:
            self.replica_restarts += 1

    def restart_replica(self, idx: int, *, stop_timeout_s: float = 30.0) -> None:
        """Operator restart of a (dead or drained) replica: bounded stop —
        failing whatever the dead loop left behind — then a fresh loop
        thread.  The breaker closes on the explicit restart; the device
        state (weights, caches, prefix registry) carries over."""
        self._restart_rep(self.replicas[idx], stop_timeout_s=stop_timeout_s)

    # ------------------------------------------------------- dynamic fleet
    def add_replica(self, engine: Optional[GenerationEngine] = None) -> str:
        """Grow the fleet by one replica and return its name — the
        autoscaler's scale-up actuator.  ``engine`` defaults to one spawned
        from the registry's ``replica_factory`` (shared ModelSpec weights;
        the factory returns a STARTED engine).  The new replica opens for
        dispatch atomically with its list append; its spawn index is
        monotonic, so names are never reused across scale cycles."""
        with self._lock:
            spawn_idx = self._spawned
            self._spawned += 1
        if engine is None:
            if self._replica_factory is None:
                with self._lock:
                    self._spawned -= 1
                raise RuntimeError(
                    "add_replica needs an engine or a replica_factory"
                )
            engine = self._replica_factory(spawn_idx)
        name = getattr(engine, "name", None) or f"replica{spawn_idx}"
        rep = _Replica(
            engine,
            name,
            CircuitBreaker(
                self._breaker_threshold, self._breaker_reset_s, clock=self._clock
            ),
        )
        if not getattr(engine, "_running", False):
            engine.start()
        self._wire_replica(rep)
        obs = getattr(engine, "obs", None)
        if obs is not None:
            obs.flight.record("replica_added", replica=name)
        with self._lock:
            self.replicas.append(rep)
            self.replicas_added += 1
        logger.info("router: added replica %s (fleet=%d)", name, len(self.replicas))
        return name

    def remove_replica(
        self,
        idx: int,
        *,
        deadline_s: float = 30.0,
        poll_s: float = 0.005,
        migrate: bool = True,
    ) -> dict:
        """Shrink the fleet by one replica: stop admitting to it, wait —
        deadline-bounded — for its in-flight work, then MIGRATE its warm KV
        state to a surviving replica's host tier, then stop and DETACH it
        (the autoscaler's scale-down actuator; drain-then-detach, no
        restart).  Safe against the replica dying mid-drain: a dead engine
        fails its in-flight work and reads idle, so the drain completes
        instead of wedging — and because the migration export is a pure
        host-memory snapshot (numpy copies, not device state), it still
        lands even when the replica died under the drain.  Without
        ``migrate`` (or without a host tier / a surviving target) the warm
        state is DROPPED and charged to ``pages_lost_at_detach`` — the
        scale-down-as-cache-wipe cost, now visible instead of silent."""
        with self._lock:
            if len(self.replicas) <= 1:
                raise RuntimeError("cannot remove the last replica")
            rep = self.replicas[idx]
            if rep.draining:
                raise RuntimeError(f"{rep.name} is already draining")
            rep.draining = True
            self.drains += 1
        obs = getattr(rep.engine, "obs", None)
        if obs is not None:
            obs.flight.record("scale_down", replica=rep.name)
        wait = self._wait_replica_idle(
            rep, deadline_s=deadline_s, poll_s=poll_s, tail="they fail on detach"
        )
        died = not rep.engine._running
        # warm-state migration BEFORE stop(): the export snapshots host
        # numpy (valid even if the engine died mid-drain — the race the
        # lock witness covers); the device registry's not-yet-spilled
        # entries are force-spilled while the engine object still exists
        migration = self._migrate_warm_state(rep, migrate=migrate)
        # stop fails anything the deadline forced (token-less victims
        # re-route through their done-callbacks, same as a replica death)
        rep.engine.stop(drain_timeout_s=1.0)
        # sliced fleet: return the replica's device slice to the planner so
        # a later scale-up can reuse those chips (idempotent release; the
        # hook exists only on slice-pinned engines).  AFTER stop(): the
        # engine must never tick on a slice another replica could acquire.
        release = getattr(rep.engine, "release_slice", None)
        if callable(release):
            try:
                release()
            except Exception:  # pragma: no cover - planner release is leaf
                logger.exception(
                    "router: slice release failed for %s", rep.name
                )
        self.prefix_registry.drop_replica(rep.name)
        with self._lock:
            if rep in self.replicas:
                self.replicas.remove(rep)
            self.replicas_removed += 1
            rep.draining = False
        report = {
            "replica": rep.name,
            "died_mid_drain": died,
            "slice_id": getattr(rep.engine, "slice_id", None),
            **wait,
            **migration,
        }
        if obs is not None:
            obs.flight.record("replica_removed", **report)
            if died or wait["forced_failures"]:
                # the race the lock witness + flight recorder exist to catch:
                # the replica died (or shed) under a scale-down — dump the
                # ring so the artifact shows the kill AND the scale decision
                obs.flight.dump("scale_down_interrupted", **report)
        logger.info(
            "router: removed replica %s (fleet=%d, drained=%s)",
            rep.name,
            len(self.replicas),
            wait["drained"],
        )
        return report

    def _migrate_warm_state(self, rep: "_Replica", *, migrate: bool) -> dict:
        """Move the detaching replica's warm prefixes into a surviving
        replica's host tier.  Returns the accounting block for the detach
        report: entries/pages migrated vs lost.  Never raises — a scale-down
        must complete even when the warm state cannot be saved."""
        eng = rep.engine
        pool = getattr(eng, "_kv_pool", None)
        src_tier = getattr(eng, "kv_host_tier", None)
        device_entries = pool.shared_keys() if pool is not None else []
        out = {
            "migrated_entries": 0,
            "migrated_pages": 0,
            "lost_entries": 0,
            "lost_pages": 0,
        }
        lost_reason = None
        if not migrate:
            lost_reason = "migration disabled"
        elif src_tier is None:
            lost_reason = "no host tier on the detaching replica"
        if lost_reason is None:
            # entries the device registry holds that write-through never
            # mirrored (writethrough=False): one last spill while the engine
            # object is whole.  A dead device makes the fetch raise — the
            # engine swallows it and those entries are charged as lost.
            try:
                eng.spill_registered_to_host()
            except Exception:
                logger.exception(
                    "migration: device-registry spill failed on %s", rep.name
                )
            # the FULL export — host DRAM plus disk rows loaded back into
            # memory (a prefix demoted to disk is still warm state; leaving
            # it behind would wipe it silently, since the victim's disk
            # namespace is swept on reuse).  Unreadable disk rows are
            # charged lost below.
            snapshot, unreadable = src_tier.export_all()
            with self._lock:
                others = [
                    r
                    for r in self.replicas
                    if r is not rep
                    and not r.draining
                    and getattr(r.engine, "kv_host_tier", None) is not None
                ]
            others = [r for r in others if self._healthy(r)]
            if not others:
                lost_reason = "no surviving replica with a host tier"
            else:
                target = min(others, key=self._load)
                # absorb() reports the snapshot keys the target RETAINS
                # (host or its disk tier) — per-key accounting, because a
                # put can be refused anywhere in the order (oversized
                # entry) or evict an earlier import
                retained = (
                    set(target.engine.kv_host_tier.absorb(snapshot))
                    if snapshot
                    else set()
                )
                pages_by_key = {e.key: e.pages for e in snapshot}
                out["migrated_entries"] = len(retained)
                out["migrated_pages"] = sum(
                    pg for key, pg in pages_by_key.items() if key in retained
                )
                # lost = export keys the target refused + disk rows whose
                # file could not be read back + device-registry entries that
                # never reached the export (spill failed / device died) —
                # keyed per unique prefix so a key present in two tiers is
                # charged once.  Accounted even when the export came back
                # EMPTY (the dead-device + writethrough-off shape: the
                # silent-wipe case pages_lost_at_detach exists to expose)
                lost: Dict[tuple, int] = {
                    key: pg
                    for key, pg in pages_by_key.items()
                    if key not in retained
                }
                for key, _ln, pg in unreadable:
                    lost.setdefault(key, pg)
                for key, _ln, pg in device_entries:
                    if key not in pages_by_key:
                        lost.setdefault(key, pg)
                out["lost_entries"] = len(lost)
                out["lost_pages"] = sum(lost.values())
                if snapshot:
                    with self._lock:
                        self.detach_migrations += 1
                        self.entries_migrated += out["migrated_entries"]
                        self.pages_migrated += out["migrated_pages"]
                    obs = getattr(eng, "obs", None)
                    if obs is not None:
                        obs.flight.record(
                            "kv_migrate",
                            from_replica=rep.name,
                            to_replica=target.name,
                            **out,
                        )
                    logger.info(
                        "router: migrated %d warm prefix entries (%d pages) "
                        "from %s to %s (%d lost)",
                        out["migrated_entries"],
                        out["migrated_pages"],
                        rep.name,
                        target.name,
                        out["lost_entries"],
                    )
        if lost_reason is not None:
            # the pre-migration bugfix half of the contract: a detach that
            # discards warm state SAYS so — counter + flight event — instead
            # of silently wiping the fleet's cache.  Count each UNIQUE
            # prefix once: with write-through most device-registry entries
            # also have a host copy, and summing both tiers would double
            # the reported loss.
            union: Dict[tuple, int] = {
                key: pg for key, _, pg in device_entries
            }
            if src_tier is not None:
                # warm_keys() spans host DRAM AND disk (no file reads) —
                # a prefix demoted to disk is warm state being discarded
                # just the same
                for key, pg in src_tier.warm_keys():
                    union.setdefault(key, pg)
            out["lost_entries"] = len(union)
            out["lost_pages"] = sum(union.values())
            out["lost_reason"] = lost_reason
        if out["lost_pages"]:
            with self._lock:
                self.pages_lost_at_detach += out["lost_pages"]
            obs = getattr(eng, "obs", None)
            if obs is not None:
                obs.flight.record(
                    "pages_lost_at_detach",
                    replica=rep.name,
                    pages=out["lost_pages"],
                    entries=out["lost_entries"],
                    reason=out.get("lost_reason", "budget/unsaved"),
                )
        return out

    # ---------------------------------------------------------------- drain
    def _replica_idle(self, rep: _Replica) -> bool:
        return rep.engine.idle()

    def _wait_replica_idle(
        self, rep: _Replica, *, deadline_s: float, poll_s: float, tail: str
    ) -> dict:
        """The drain-wait core shared by graceful drain (restart epilogue)
        and scale-down (detach epilogue): poll until the replica holds no
        accepted work or the deadline lands, charging ``drain_shed`` for
        whatever the deadline forces.  ``tail`` names the caller's fate for
        the forced work in the log line."""
        t0 = self._clock()
        while not self._replica_idle(rep) and self._clock() - t0 < deadline_s:
            self._sleep(poll_s)
        drained = self._replica_idle(rep)
        forced = 0
        if not drained:
            forced = rep.engine.num_active + rep.engine.queued_depth()
            with self._lock:
                self.drain_shed += forced
            logger.warning(
                "router: drain of %s hit its %.1fs deadline with %d "
                "request(s) still in flight; %s",
                rep.name,
                deadline_s,
                forced,
                tail,
            )
        return {
            "drained": drained,
            "forced_failures": forced,
            "waited_s": round(self._clock() - t0, 3),
        }

    def drain(
        self,
        idx: int,
        *,
        deadline_s: float = 30.0,
        restart: bool = True,
        poll_s: float = 0.005,
    ) -> dict:
        """Gracefully drain one replica: stop admitting to it (the rest of
        the fleet absorbs traffic), wait — deadline-bounded — for its
        in-flight and queued work to finish, then restart it.  Returns a
        summary dict; ``forced_failures`` counts requests the deadline
        forced to fail (0 on a clean drain — the zero-shed rolling-restart
        contract)."""
        return self._drain_rep(
            self.replicas[idx], deadline_s=deadline_s, restart=restart, poll_s=poll_s
        )

    def _drain_rep(
        self,
        rep: "_Replica",
        *,
        deadline_s: float = 30.0,
        restart: bool = True,
        poll_s: float = 0.005,
    ) -> dict:
        with self._lock:
            if rep not in self.replicas:
                # a concurrent remove_replica (autoscaler scale-down) won the
                # race: the replica is already detached and stopped — there
                # is nothing to drain and NOTHING to restart (restarting a
                # detached engine would orphan a running loop no dispatch
                # can reach and no stop() will ever visit)
                return {
                    "replica": rep.name,
                    "drained": True,
                    "forced_failures": 0,
                    "waited_s": 0.0,
                    "skipped": "detached",
                }
            if rep.draining:
                raise RuntimeError(f"{rep.name} is already draining")
            rep.draining = True
            self.drains += 1
        obs = getattr(rep.engine, "obs", None)
        if obs is not None:
            obs.flight.record("drain_begin", replica=rep.name)
        try:
            wait = self._wait_replica_idle(
                rep,
                deadline_s=deadline_s,
                poll_s=poll_s,
                tail="they fail on restart",
            )
            drained, forced = wait["drained"], wait["forced_failures"]
            if restart:
                with self._lock:
                    still_attached = rep in self.replicas
                if still_attached:
                    self._restart_rep(rep)
            if obs is not None:
                obs.flight.record(
                    "drain_end",
                    replica=rep.name,
                    drained=drained,
                    forced_failures=forced,
                )
                # a forced drain killed work the replica promised to finish:
                # that is a post-mortem artifact, same as a crash restart
                if forced:
                    obs.flight.dump("drain_forced", replica=rep.name, forced=forced)
            return {"replica": rep.name, **wait}
        finally:
            with self._lock:
                rep.draining = False

    def rolling_restart(self, *, deadline_s: float = 30.0) -> List[dict]:
        """Drain-and-restart every replica, one at a time, under live
        traffic — the zero-downtime restart path.  With >= 2 replicas the
        fleet keeps serving throughout.  Snapshots the fleet first: replicas
        an autoscaler adds mid-restart are already fresh, and ones it drains
        or detaches concurrently are SKIPPED (reported, not fatal) — an
        aborted rolling restart would leave the tail of the fleet on the old
        state."""
        with self._lock:
            reps = list(self.replicas)
        reports = []
        for rep in reps:
            try:
                reports.append(
                    self._drain_rep(rep, deadline_s=deadline_s, restart=True)
                )
            except RuntimeError as e:
                # concurrently draining (autoscaler scale-down mid-flight):
                # that drain already does the work this pass wanted
                reports.append({"replica": rep.name, "skipped": str(e)})
        return reports

    def begin_drain(self) -> None:
        """Non-blocking fleet-wide admission stop (the SIGTERM path): every
        replica is marked draining so dispatch fails fast while in-flight
        work keeps running.  The caller owns the wait (the server's shutdown
        handler polls ``idle()``); :meth:`drain_all` wraps both."""
        with self._lock:
            for rep in self.replicas:
                rep.draining = True

    def drain_all(self, *, deadline_s: float = 30.0, poll_s: float = 0.01) -> bool:
        """Whole-router drain (SIGTERM): stop admitting everywhere, wait for
        the fleet to finish what it accepted.  Returns True when everything
        drained inside the deadline.  No restart — the process is exiting."""
        self.begin_drain()
        t0 = self._clock()
        while self._clock() - t0 < deadline_s:
            if all(self._replica_idle(rep) for rep in list(self.replicas)):
                return True
            self._sleep(poll_s)
        return all(self._replica_idle(rep) for rep in list(self.replicas))

    # ------------------------------------------------------- engine surface
    # (aggregates snapshot the fleet list: add_replica/remove_replica mutate
    # it under the router lock while these read from scrape/HTTP threads)
    @property
    def num_active(self) -> int:
        return sum(rep.engine.num_active for rep in list(self.replicas))

    @property
    def steps(self) -> int:
        return sum(rep.engine.steps for rep in list(self.replicas))

    @property
    def reclaimed_slots(self) -> int:
        return sum(rep.engine.reclaimed_slots for rep in list(self.replicas))

    @property
    def cancelled_slots(self) -> int:
        return sum(rep.engine.cancelled_slots for rep in list(self.replicas))

    def queued_depth(self) -> int:
        return sum(rep.engine.queued_depth() for rep in list(self.replicas))

    def idle(self) -> bool:
        return all(rep.engine.idle() for rep in list(self.replicas))

    def holds_prefix(self, prompt_ids: Sequence[int], prefix_len: int) -> bool:
        return any(
            rep.engine.holds_prefix(prompt_ids, prefix_len)
            for rep in list(self.replicas)
        )

    def start(self) -> "EngineRouter":
        for rep in list(self.replicas):
            rep.engine.start()
        return self

    def stop(self, drain_timeout_s: float = 120.0) -> None:
        for rep in list(self.replicas):
            rep.engine.stop(drain_timeout_s=drain_timeout_s)

    # --------------------------------------------------------------- stats
    def router_stats(self) -> dict:
        """Fleet gauges for tick_stats / healthz: per-replica depth and
        breaker state, affinity hit rate, re-routes, drains.

        The router lock covers ONLY the router-owned counters.  Per-replica
        depth goes through ``queued_depth()`` → the replica's scheduler lock,
        and a dying replica's engine thread resolves futures UNDER that
        scheduler lock whose done-callbacks take the router lock — holding
        the router lock across the engine call would be the classic ABBA
        deadlock, wedging /healthz and every submit the moment a probe races
        a replica death."""
        with self._lock:
            hits, misses = self.affinity_hits, self.affinity_misses
            reps = list(self.replicas)
            out = {
                "n_replicas": len(reps),
                "affinity_hits": hits,
                "affinity_misses": misses,
                "affinity_hit_rate": round(hits / max(1, hits + misses), 4),
                "reroutes": self.reroutes,
                "rerouted_failed": self.rerouted_failed,
                "failed_past_first_token": self.failed_past_first_token,
                "drains": self.drains,
                "drain_shed": self.drain_shed,
                "no_replica_available": self.no_replica_available,
                "replicas_added": self.replicas_added,
                "replicas_removed": self.replicas_removed,
                "replica_restarts": self.replica_restarts,
                "pages_lost_at_detach": self.pages_lost_at_detach,
                "pages_migrated": self.pages_migrated,
                "entries_migrated": self.entries_migrated,
                "detach_migrations": self.detach_migrations,
            }
        # fleet prefix registry block (its own leaf lock — never nested
        # under the router lock)
        out["prefix_registry"] = self.prefix_registry.stats()
        out["replicas"] = [
            {
                "name": rep.name,
                "depth": rep.engine.queued_depth(),
                "active": rep.engine.num_active,
                "breaker": rep.breaker.state,
                "draining": rep.draining,
                "healthy": self._healthy(rep),
                "dispatched": rep.dispatched,
                "completed_ok": rep.completed_ok,
                "slice_id": getattr(rep.engine, "slice_id", None),
            }
            for rep in reps
        ]
        # slice capacity (sliced fleets): total/free slices next to the
        # fleet size, so "at hardware limit" is readable off one surface
        if self.mesh_planner is not None:
            ps = self.mesh_planner.stats()
            out["slices_total"] = ps["slices_total"]
            out["slices_free"] = ps["slices_free"]
            out["replica_devices"] = ps["replica_devices"]
        return out

    def slice_stats(self) -> dict:
        """Fleet slice topology for /healthz (docs/MULTICHIP.md): the
        planner's capacity snapshot plus each replica's slice identity and
        per-slice HBM ledger (engines without the surface — stubs — are
        skipped)."""
        out: dict = {
            "planner": (
                self.mesh_planner.stats()
                if self.mesh_planner is not None
                else None
            ),
        }
        per = []
        for rep in list(self.replicas):
            fn = getattr(rep.engine, "slice_stats", None)
            if callable(fn):
                s = fn()
                s["name"] = rep.name
                per.append(s)
        out["replicas"] = per
        return out

    def latency_stats(self) -> dict:
        """Fleet-wide perceived-latency percentiles: the replicas' raw TTFT /
        ITL sample windows concatenated (percentiles cannot be merged from
        per-replica percentiles)."""
        ttft: List[float] = []
        itl: List[float] = []
        for rep in list(self.replicas):
            ttft.extend(rep.engine._ttft_s)
            itl.extend(rep.engine._itl_s)
        p = GenerationEngine._pctl_ms
        return {
            "ttft_p50_ms": p(ttft, 0.50),
            "ttft_p95_ms": p(ttft, 0.95),
            "ttft_n": len(ttft),
            "itl_p50_ms": p(itl, 0.50),
            "itl_p95_ms": p(itl, 0.95),
            "itl_n": len(itl),
            "cancelled_slots": self.cancelled_slots,
        }

    def kv_stats(self) -> dict:
        """Aggregated KV gauges + the per-replica blocks (each carries its
        own kv_layout_requested/effective so one replica silently on the
        legacy plane is visible)."""
        per = [rep.engine.kv_stats() for rep in list(self.replicas)]
        layouts = {p["kv_layout_effective"] for p in per}
        out: dict = {
            "kv_layout": per[0]["kv_layout"] if len(layouts) == 1 else "mixed",
            "kv_layout_requested": per[0]["kv_layout_requested"],
            "kv_layout_effective": layouts.pop() if len(layouts) == 1 else "mixed",
            "prefix_hits": sum(p.get("prefix_hits", 0) for p in per),
            "prefix_misses": sum(p.get("prefix_misses", 0) for p in per),
            "replicas": per,
        }
        if all("kv_pages_total" in p for p in per):
            for key in ("kv_pages_total", "kv_pages_used", "kv_pages_free"):
                out[key] = sum(p[key] for p in per)
            if all("kv_pages_obtainable" in p for p in per):
                out["kv_pages_obtainable"] = sum(
                    p["kv_pages_obtainable"] for p in per
                )
        return out

    def decode_path_stats(self) -> dict:
        """Fleet decode fast-path gauges (docs/QUANT.md): fused depth /
        weight bits from the replicas (uniform by construction — every
        replica is built from the same spec), effective depth as the MIN
        across replicas (one json-downgraded replica is what an operator
        must see), counters summed, per-replica blocks attached."""
        per = [rep.engine.decode_path_stats() for rep in list(self.replicas)]
        if not per:
            return {}
        return {
            "decode_steps": per[0]["decode_steps"],
            "decode_steps_effective": min(
                p["decode_steps_effective"] for p in per
            ),
            "json_downgraded_ticks": sum(
                p["json_downgraded_ticks"] for p in per
            ),
            "upload_overlap_frac": round(
                sum(p["upload_overlap_frac"] for p in per) / len(per), 4
            ),
            "weight_bits": per[0]["weight_bits"],
            # continuous batching: the fleet displacement fraction is the
            # mean (every replica ticks at roughly the same rate), chunks
            # piggybacked is a plain counter sum; the feature flags are
            # uniform by construction
            "prefill_piggyback": per[0].get("prefill_piggyback", False),
            "prefill_chunks_piggybacked": sum(
                p.get("prefill_chunks_piggybacked", 0) for p in per
            ),
            "prefill_displacement_frac": round(
                sum(p.get("prefill_displacement_frac", 0.0) for p in per)
                / len(per),
                4,
            ),
            "attn_fp8": per[0].get("attn_fp8", False),
            "replicas": per,
        }

    def supervision_stats(self) -> dict:
        """Aggregate supervision: healthy only when EVERY replica is (one
        dead replica of N is exactly what an operator must see as degraded),
        with the per-replica blocks attached for /healthz."""
        per = []
        for rep in list(self.replicas):
            s = rep.engine.supervision_stats()
            s["name"] = rep.name
            s["breaker"] = rep.breaker.state
            s["draining"] = rep.draining
            per.append(s)
        return {
            "running": any(p["running"] for p in per),
            "healthy": all(p["healthy"] for p in per),
            "degraded": any(p["degraded"] for p in per),
            "replicas": per,
            "engine_restarts": sum(p["engine_restarts"] for p in per),
            "poisoned_requests": sum(p["poisoned_requests"] for p in per),
            "circuit_trips": sum(p["circuit_trips"] for p in per),
            "restarted_requests_resubmitted": sum(
                p["restarted_requests_resubmitted"] for p in per
            ),
            "restarted_requests_failed": sum(
                p["restarted_requests_failed"] for p in per
            ),
            "reroutes": self.reroutes,
        }

    def tick_stats(self) -> dict:
        """Fleet tick_stats: router gauges + aggregated latency/KV/supervision
        plus each replica's full engine tick_stats block."""
        out = {
            "router": self.router_stats(),
            "kv": self.kv_stats(),
            "supervision": self.supervision_stats(),
            "replicas": [rep.engine.tick_stats() for rep in list(self.replicas)],
        }
        out.update(self.latency_stats())
        return out
