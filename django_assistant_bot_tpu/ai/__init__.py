"""AI provider abstraction — uniform async chat + embedding interface.

Reference parity (assistant/ai/): the same two ABCs (`AIProvider`, `AIEmbedder`),
the same prefix-routed factories, the same `AIResponse`/`Message` domain types and
`AIDialog` wrapper — plus the new ``tpu:`` prefix that routes to the in-process
TPU serving plane instead of an out-of-process microservice.
"""

from .dialog import AIDialog  # noqa: F401
from .domain import AIResponse, Message, assistant_message, system_message, user_message  # noqa: F401
from .providers.base import AIDebugger, AIEmbedder, AIProvider  # noqa: F401
from .services.ai_service import (  # noqa: F401
    calculate_ai_cost,
    extract_tagged_text,
    get_ai_embedder,
    get_ai_provider,
)
