"""`tpu:` provider — in-process continuous-batching generation on the TPU mesh.

The flagship provider: where the reference hops HTTP to a FastAPI+torch
microservice (reference: assistant/ai/providers/gpu_service.py:9-41 →
gpu_service/main.py:89-107), this drives the serving engine directly in-process —
no serialization hop, shared mesh, cross-request continuous batching.

The process-wide registry is built lazily from ``settings.TPU_SERVING_CONFIG``
(TOML/JSON: model name -> ModelSpec dict) or falls back to tiny random-weight
models so dev/test environments need no checkpoints.
"""

from __future__ import annotations

import json
import threading
from typing import List, Optional

from ...conf import settings
from ...utils.repeat_until import RepeatUntilError, repeat_until
from ..domain import AIResponse, Message
from .base import AIEmbedder, AIProvider, AIStreamChunk, parse_json_response

_registry = None
_registry_lock = threading.Lock()


def get_shared_registry():
    """Process-wide ModelRegistry for all `tpu:` providers/embedders."""
    global _registry
    with _registry_lock:
        if _registry is None:
            from ...serving.registry import ModelRegistry

            config = {}
            path = settings.TPU_SERVING_CONFIG
            if path:
                if path.endswith(".toml"):
                    import tomllib

                    with open(path, "rb") as f:
                        config = tomllib.load(f).get("models", {})
                else:
                    with open(path) as f:
                        config = json.load(f).get("models", {})
            _registry = ModelRegistry.from_config(config)
        return _registry


def reset_shared_registry():
    global _registry
    with _registry_lock:
        if _registry is not None:
            _registry.stop()
        _registry = None


def _ensure_loaded(name: str, kind: str):
    """Load on first use; unknown names load as tiny random models (dev mode).

    Check-and-load runs under the registry lock: concurrent first-use of the
    same model must not allocate two engines (the loser would leak its device
    memory and batcher thread).
    """
    from ...serving.registry import ModelSpec

    reg = get_shared_registry()
    getter = reg.get_embedder if kind == "encoder" else reg.get_generator
    with _registry_lock:
        eng = getter(name)
        if eng is None:
            reg.load(
                ModelSpec(
                    name=name.lower(),
                    kind=kind,
                    tiny=True,
                    dtype="float32",
                    # a byte-tokenized RAG-enriched prompt easily exceeds the
                    # tiny factory's 256-token context; dev-mode decoders get
                    # room to actually answer (submit() truncates otherwise,
                    # leaving ~1 token of generation headroom)
                    max_seq_len=1024 if kind == "decoder" else None,
                )
            )
            eng = getter(name)
    return eng


class TPUProvider(AIProvider):
    """In-process provider.  ``priority``/``tenant``/``deadline_s`` tag every
    request end-to-end into the serving scheduler: interactive dialog turns
    outrank background ingestion (question/sentence generation) without a
    second model replica — see serving/scheduler.py."""

    def __init__(
        self,
        model: str,
        *,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
    ):
        self._model = model
        self._priority = priority
        self._tenant = tenant
        self._deadline_s = deadline_s
        self.calls_attempts: List[int] = []
        self._engine = _ensure_loaded(model, "decoder")

    @property
    def context_size(self) -> int:
        return self._engine.max_seq_len

    def calculate_tokens(self, text: str) -> int:
        return len(self._engine.tokenizer.encode(text))

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        attempts = 0

        async def call() -> AIResponse:
            nonlocal attempts
            attempts += 1
            result = await self._engine.generate(
                list(messages),
                max_tokens=max_tokens,
                temperature=0.8,
                json_format=json_format,
                priority=self._priority,
                tenant=self._tenant,
                deadline_s=self._deadline_s,
            )
            return AIResponse(
                result=result.text,
                usage=result.usage_dict(self._model),
                length_limited=result.length_limited,
            )

        if not json_format:
            resp = await call()
            self.calls_attempts.append(attempts)
            return resp

        def valid_json(resp: AIResponse):
            parsed, err = parse_json_response(resp.result)
            if err:
                return err
            resp.result = parsed
            return True

        try:
            resp = await repeat_until(call, condition=valid_json, max_attempts=5)
        except RepeatUntilError as e:
            resp = e.last_result
            parsed, _ = parse_json_response(resp.result)
            resp.result = parsed if parsed is not None else {}
        self.calls_attempts.append(attempts)
        return resp

    async def stream_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ):
        """Native in-process stream: tokens flow straight from the decode tick
        (engine.generate_stream) with no HTTP hop.  ``json_format`` output is
        only valid as a whole document (the repair/repeat loop may rewrite
        it), so it buffers through the base adapter instead."""
        if json_format:
            async for chunk in AIProvider.stream_response(
                self, messages, max_tokens=max_tokens, json_format=True
            ):
                yield chunk
            return
        self.calls_attempts.append(1)
        agen = self._engine.generate_stream(
            list(messages),
            max_tokens=max_tokens,
            temperature=0.8,
            priority=self._priority,
            tenant=self._tenant,
            deadline_s=self._deadline_s,
        )
        async for c in agen:
            if c.done:
                r = c.result
                if c.text:
                    yield AIStreamChunk(delta=c.text)
                yield AIStreamChunk(
                    done=True,
                    response=AIResponse(
                        result=r.text,
                        usage=r.usage_dict(self._model),
                        length_limited=r.length_limited,
                    ),
                )
                return
            if c.text:
                yield AIStreamChunk(delta=c.text)


class TPUEmbedder(AIEmbedder):
    def __init__(self, model: str):
        self._model = model
        self._engine = _ensure_loaded(model, "encoder")

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        return await self._engine.embed(list(input))
