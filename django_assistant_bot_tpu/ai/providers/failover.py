"""Provider failover chain with per-backend circuit breakers.

The reference (and this repo, until now) pins each bot to exactly ONE provider:
when that backend is down — the TPU engine degraded (503), the gpu_service
unreachable, an API quota blown — every dialog turn fails until a human edits
config.  ``FailoverProvider`` wraps an *ordered* chain (e.g. ``tpu:chat`` →
``gpu_service:chat`` → ``test``) and serves each request from the first
healthy backend:

- **Per-backend circuit breaker** (closed → open → half-open).  A backend that
  keeps failing is skipped for ``reset_timeout_s`` instead of eating its
  timeout on every request; after the cooldown exactly one probe request is
  let through (half-open) — success closes the circuit, failure re-opens it.
- **Per-attempt timeout.**  A hung backend costs at most ``attempt_timeout_s``
  before the chain moves on (None = the backend's own timeout discipline).
- **Jittered backoff between backends** bounds the thundering retry a mass
  failure would otherwise produce.
- **Streaming-aware.**  ``stream_response`` fails over only while nothing has
  been emitted: once the first delta reaches the consumer the response is
  committed, and a mid-stream error surfaces to the client (replaying from a
  different backend would emit divergent text after the prefix).

Construction is routed from model strings of the form
``failover:<model>|<model>|...`` (ai/services/ai_service.py), so a bot config
opts in without code changes.  Deterministic tests inject a fake clock/sleep.
"""

from __future__ import annotations

import asyncio
import contextlib
import logging
import random
import threading
import time
from typing import Callable, List, Optional, Sequence

from ..domain import AIResponse, Message
from .base import AIProvider

logger = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class AllBackendsFailed(RuntimeError):
    """Every backend in the chain failed (or had its circuit open)."""

    def __init__(self, errors: Sequence[tuple]):
        detail = "; ".join(f"{name}: {type(e).__name__}: {e}" for name, e in errors)
        super().__init__(f"all {len(errors)} failover backends failed ({detail})")
        self.errors = list(errors)


class CircuitBreaker:
    """Minimal closed/open/half-open breaker, deterministic under a fake clock.

    ``failure_threshold`` consecutive failures open the circuit; after
    ``reset_timeout_s`` ONE caller is admitted as a half-open probe (further
    callers stay blocked until it resolves); the probe's success closes the
    circuit, its failure re-opens the full timeout.

    Thread-safe: the provider failover chain runs on one event loop, but the
    multi-replica engine router mutates breakers from HTTP event-loop threads
    (dispatch) and engine threads (completion callbacks) concurrently — an
    unguarded ``allow()`` would admit two half-open probes at once.
    """

    def __init__(
        self,
        failure_threshold: int = 3,
        reset_timeout_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout_s = float(reset_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            if self._opened_at is None:
                return CLOSED
            if (
                self._probing
                or self._clock() - self._opened_at >= self.reset_timeout_s
            ):
                return HALF_OPEN
            return OPEN

    def allow(self) -> bool:
        """May a request try this backend right now?  (Half-open admits one.)"""
        with self._lock:
            if self._opened_at is None:
                return True
            if self._probing:
                return False  # one probe at a time
            if self._clock() - self._opened_at >= self.reset_timeout_s:
                self._probing = True
                return True
            return False

    def retry_in_s(self) -> float:
        """Seconds until this breaker would admit a request again (0 = now).
        The engine router derives an honest ``Retry-After`` for the
        no-healthy-replica 503 from the soonest breaker instead of a fixed
        constant; a breaker mid-probe reports the full timeout (the probe
        slot is taken — the caller would be rejected until it resolves)."""
        with self._lock:
            if self._opened_at is None:
                return 0.0
            if self._probing:
                return self.reset_timeout_s
            return max(
                0.0, self.reset_timeout_s - (self._clock() - self._opened_at)
            )

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._probing = False

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._probing or self._failures >= self.failure_threshold:
                self._opened_at = self._clock()  # (re-)open the full timeout
                self._probing = False

    def release_probe(self) -> None:
        """The admitted half-open probe resolved neither way (the caller was
        cancelled mid-flight): free the probe slot so the NEXT request can
        probe — without this the breaker would stay half-open-and-blocking
        forever.  No-op unless a probe is outstanding."""
        with self._lock:
            self._probing = False


class FailoverProvider(AIProvider):
    """Ordered provider chain behind one :class:`AIProvider` face."""

    def __init__(
        self,
        providers: Sequence[AIProvider],
        *,
        names: Optional[Sequence[str]] = None,
        attempt_timeout_s: Optional[float] = None,
        backoff_s: float = 0.1,
        breaker_threshold: int = 3,
        breaker_reset_s: float = 30.0,
        clock: Callable[[], float] = time.monotonic,
        sleep=asyncio.sleep,
    ):
        if not providers:
            raise ValueError("failover chain needs at least one provider")
        self._providers = list(providers)
        self._names = list(names) if names else [
            type(p).__name__ for p in self._providers
        ]
        self._attempt_timeout_s = attempt_timeout_s
        self._backoff_s = max(0.0, float(backoff_s))
        self._breakers = [
            CircuitBreaker(breaker_threshold, breaker_reset_s, clock=clock)
            for _ in self._providers
        ]
        self._sleep = sleep
        self.calls_attempts: List[int] = []

    # ------------------------------------------------------------------ stats
    def breaker_states(self) -> dict:
        return {n: b.state for n, b in zip(self._names, self._breakers)}

    @property
    def context_size(self) -> int:
        # the chain's contract is the primary's; a fallback with a smaller
        # window truncates exactly as it would when addressed directly
        return self._providers[0].context_size

    def calculate_tokens(self, text: str) -> int:
        return self._providers[0].calculate_tokens(text)

    async def _backoff(self) -> None:
        if self._backoff_s:
            await self._sleep(self._backoff_s * (0.5 + 0.5 * random.random()))

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        errors: List[tuple] = []
        attempts = 0
        for i, (name, prov, br) in enumerate(
            zip(self._names, self._providers, self._breakers)
        ):
            if not br.allow():
                continue
            attempts += 1
            try:
                coro = prov.get_response(
                    messages, max_tokens=max_tokens, json_format=json_format
                )
                if self._attempt_timeout_s is not None:
                    resp = await asyncio.wait_for(coro, self._attempt_timeout_s)
                else:
                    resp = await coro
            except asyncio.CancelledError:
                # the CALLER went away — neither a success nor a failure of
                # this backend; free the half-open probe slot if we held it
                br.release_probe()
                raise
            except Exception as e:
                br.record_failure()
                errors.append((name, e))
                logger.warning(
                    "failover: backend %s failed (%s: %s); breaker %s",
                    name, type(e).__name__, e, br.state,
                )
                if i < len(self._providers) - 1:
                    await self._backoff()
                continue
            br.record_success()
            self.calls_attempts.append(attempts)
            return resp
        self.calls_attempts.append(attempts)
        if not errors:
            raise AllBackendsFailed(
                [(n, RuntimeError("circuit open")) for n in self._names]
            )
        raise AllBackendsFailed(errors)

    async def stream_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ):
        """Stream from the first backend that produces a chunk.  Failover
        happens only BEFORE anything is yielded; once a delta is out, the
        response is committed to that backend and a later error propagates."""
        errors: List[tuple] = []
        attempts = 0
        for i, (name, prov, br) in enumerate(
            zip(self._names, self._providers, self._breakers)
        ):
            if not br.allow():
                continue
            attempts += 1
            agen = prov.stream_response(
                messages, max_tokens=max_tokens, json_format=json_format
            )
            try:
                if self._attempt_timeout_s is not None:
                    first = await asyncio.wait_for(
                        agen.__anext__(), self._attempt_timeout_s
                    )
                else:
                    first = await agen.__anext__()
            except asyncio.CancelledError:
                # caller cancelled mid-await: free the probe slot and close
                # the backend stream before propagating
                br.release_probe()
                with contextlib.suppress(Exception):
                    await agen.aclose()
                raise
            except GeneratorExit:
                # finalization of THIS generator while suspended at the
                # backend await (consumer abandoned it without cancelling):
                # the probe slot must still free, but awaiting here is
                # illegal — if the backend's cleanup suspended, this
                # generator would yield mid-finalization and CPython raises
                # "async generator ignored GeneratorExit".  The inner
                # generator is finalized by the loop's asyncgen hooks.
                br.release_probe()
                raise
            except StopAsyncIteration:
                # an empty stream is a broken backend, not a committed answer
                br.record_failure()
                errors.append((name, RuntimeError("empty stream")))
                continue
            except Exception as e:
                br.record_failure()
                errors.append((name, e))
                logger.warning(
                    "failover: backend %s failed before first delta (%s: %s)",
                    name, type(e).__name__, e,
                )
                await agen.aclose()
                if i < len(self._providers) - 1:
                    await self._backoff()
                continue
            # committed: the consumer sees this backend's stream to the end
            br.record_success()
            self.calls_attempts.append(attempts)
            try:
                yield first
                async for chunk in agen:
                    yield chunk
            finally:
                await agen.aclose()
            return
        self.calls_attempts.append(attempts)
        if not errors:
            raise AllBackendsFailed(
                [(n, RuntimeError("circuit open")) for n in self._names]
            )
        raise AllBackendsFailed(errors)
