"""`gpu_service:` provider/embedder — HTTP client for the gpu_service contract.

Exact wire parity with the reference client (assistant/ai/providers/gpu_service.py:
9-41, assistant/ai/embedders/gpu_service.py:8-28), so it interoperates with BOTH the
reference's torch microservice and this framework's own TPU server
(:mod:`~django_assistant_bot_tpu.serving.server`) unchanged.
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import List, Optional

import aiohttp

from ..domain import AIResponse, Message
from .base import (
    AIEmbedder,
    AIProvider,
    AIStreamChunk,
    approx_tokens,
    parse_json_response,
)

logger = logging.getLogger(__name__)

# load-shed (429) retry policy: bounded attempts, Retry-After-honoring sleeps
SHED_RETRIES = 3
SHED_MAX_SLEEP_S = 10.0


async def _iter_sse_lines(content):
    """Split an SSE body into lines WITHOUT aiohttp's readline (its 64 KiB
    line cap would reject the terminal event, which carries the whole result
    text in one ``data:`` line on long generations)."""
    buf = b""
    async for chunk in content.iter_any():
        buf += chunk
        while b"\n" in buf:
            raw, buf = buf.split(b"\n", 1)
            yield raw.decode("utf-8", errors="replace").strip()
    if buf:
        yield buf.decode("utf-8", errors="replace").strip()


async def _post_with_shed_retry(session, url: str, payload: dict):
    """POST, honoring 429 + ``Retry-After`` from the scheduler's load shedding:
    sleep the hinted back-off (capped) and retry a bounded number of times;
    a still-shedding server surfaces the final 429 to the caller."""
    for attempt in range(SHED_RETRIES + 1):
        resp = await session.post(url, json=payload)
        if resp.status != 429 or attempt == SHED_RETRIES:
            resp.raise_for_status()
            return resp
        try:
            retry_after = float(resp.headers.get("Retry-After", "1"))
        except ValueError:
            retry_after = 1.0
        resp.release()
        logger.info(
            "%s shed the request (429); retrying in %.1fs (%d/%d)",
            url, retry_after, attempt + 1, SHED_RETRIES,
        )
        await asyncio.sleep(min(SHED_MAX_SLEEP_S, max(0.0, retry_after)))
    raise RuntimeError("unreachable")  # pragma: no cover


class GPUServiceProvider(AIProvider):
    def __init__(
        self,
        base_url: str,
        model: str,
        timeout_s: float = 120.0,
        *,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
    ):
        self._base = base_url.rstrip("/")
        self._model = model
        self._priority = priority
        self._tenant = tenant
        self._deadline_s = deadline_s
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self.calls_attempts: List[int] = []

    @property
    def context_size(self) -> int:
        return 8000  # reference hardcodes this (assistant/ai/providers/openai.py:22)

    def calculate_tokens(self, text: str) -> int:
        return approx_tokens(text)

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        self.calls_attempts.append(1)
        payload = {
            "model": self._model,
            "messages": list(messages),
            "max_tokens": max_tokens,
            "json_format": json_format,
            "priority": self._priority,
            "tenant": self._tenant,
        }
        if self._deadline_s is not None:
            payload["deadline_s"] = self._deadline_s
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with await _post_with_shed_retry(
                session, f"{self._base}/dialog/", payload
            ) as resp:
                data = await resp.json()
        body = data["response"]
        result = body["result"]
        if json_format and isinstance(result, str):
            parsed, _ = parse_json_response(result)
            result = parsed if parsed is not None else {}
        return AIResponse(
            result=result,
            usage=body.get("usage"),
            length_limited=body.get("length_limited", False),
        )

    async def stream_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ):
        """Consume the server's ``text/event-stream`` wire format
        (docs/STREAMING.md): per-delta ``data:`` events, a terminal event with
        usage + the authoritative full text, then ``[DONE]``.  The server
        rejects ``stream`` + ``json_format`` (422), so JSON requests buffer
        through the base adapter here."""
        if json_format:
            async for chunk in AIProvider.stream_response(
                self, messages, max_tokens=max_tokens, json_format=True
            ):
                yield chunk
            return
        self.calls_attempts.append(1)
        payload = {
            "model": self._model,
            "messages": list(messages),
            "max_tokens": max_tokens,
            "json_format": False,
            "stream": True,
            "priority": self._priority,
            "tenant": self._tenant,
        }
        if self._deadline_s is not None:
            payload["deadline_s"] = self._deadline_s
        acc: List[str] = []
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with await _post_with_shed_retry(
                session, f"{self._base}/dialog/", payload
            ) as resp:
                async for line in _iter_sse_lines(resp.content):
                    if not line.startswith("data:"):
                        continue
                    data = line[len("data:"):].strip()
                    if data == "[DONE]":
                        break
                    event = json.loads(data)
                    if event.get("done"):
                        if event.get("finish_reason") == "error":
                            raise RuntimeError(
                                f"stream failed mid-generation: "
                                f"{event.get('error', 'unknown error')}"
                            )
                        result = event.get("result")
                        yield AIStreamChunk(
                            done=True,
                            response=AIResponse(
                                result="".join(acc) if result is None else result,
                                usage=event.get("usage"),
                                length_limited=event.get("length_limited", False),
                            ),
                        )
                        return
                    delta = event.get("delta", "")
                    if delta:
                        acc.append(delta)
                        yield AIStreamChunk(delta=delta)
        # stream closed without a terminal event (server died mid-stream):
        # surface what arrived rather than silently dropping the turn
        yield AIStreamChunk(
            done=True,
            response=AIResponse(result="".join(acc), usage=None, length_limited=False),
        )


class GPUServiceEmbedder(AIEmbedder):
    def __init__(self, base_url: str, model: str, timeout_s: float = 120.0):
        self._base = base_url.rstrip("/")
        self._model = model
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        payload = {"model": self._model, "texts": list(input)}
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with await _post_with_shed_retry(
                session, f"{self._base}/embeddings/", payload
            ) as resp:
                data = await resp.json()
        return data["embeddings"]
