"""`gpu_service:` provider/embedder — HTTP client for the gpu_service contract.

Exact wire parity with the reference client (assistant/ai/providers/gpu_service.py:
9-41, assistant/ai/embedders/gpu_service.py:8-28), so it interoperates with BOTH the
reference's torch microservice and this framework's own TPU server
(:mod:`~django_assistant_bot_tpu.serving.server`) unchanged.
"""

from __future__ import annotations

from typing import List

import aiohttp

from ..domain import AIResponse, Message
from .base import AIEmbedder, AIProvider, approx_tokens, parse_json_response


class GPUServiceProvider(AIProvider):
    def __init__(self, base_url: str, model: str, timeout_s: float = 120.0):
        self._base = base_url.rstrip("/")
        self._model = model
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self.calls_attempts: List[int] = []

    @property
    def context_size(self) -> int:
        return 8000  # reference hardcodes this (assistant/ai/providers/openai.py:22)

    def calculate_tokens(self, text: str) -> int:
        return approx_tokens(text)

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        self.calls_attempts.append(1)
        payload = {
            "model": self._model,
            "messages": list(messages),
            "max_tokens": max_tokens,
            "json_format": json_format,
        }
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with session.post(f"{self._base}/dialog/", json=payload) as resp:
                resp.raise_for_status()
                data = await resp.json()
        body = data["response"]
        result = body["result"]
        if json_format and isinstance(result, str):
            parsed, _ = parse_json_response(result)
            result = parsed if parsed is not None else {}
        return AIResponse(
            result=result,
            usage=body.get("usage"),
            length_limited=body.get("length_limited", False),
        )


class GPUServiceEmbedder(AIEmbedder):
    def __init__(self, base_url: str, model: str, timeout_s: float = 120.0):
        self._base = base_url.rstrip("/")
        self._model = model
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        payload = {"model": self._model, "texts": list(input)}
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with session.post(f"{self._base}/embeddings/", json=payload) as resp:
                resp.raise_for_status()
                data = await resp.json()
        return data["embeddings"]
