"""`gpu_service:` provider/embedder — HTTP client for the gpu_service contract.

Exact wire parity with the reference client (assistant/ai/providers/gpu_service.py:
9-41, assistant/ai/embedders/gpu_service.py:8-28), so it interoperates with BOTH the
reference's torch microservice and this framework's own TPU server
(:mod:`~django_assistant_bot_tpu.serving.server`) unchanged.
"""

from __future__ import annotations

import asyncio
import datetime
import email.utils
import json
import logging
import random
import uuid
from typing import List, Optional

import aiohttp

from ..domain import AIResponse, Message
from .base import (
    AIEmbedder,
    AIProvider,
    AIStreamChunk,
    approx_tokens,
    parse_json_response,
)

logger = logging.getLogger(__name__)

# retry policy: bounded attempts; 429/503 honor Retry-After (float seconds or
# RFC 9110 HTTP-date), connection errors/timeouts use capped jittered backoff.
# 503 and connection errors retry only for idempotent requests — every call in
# this module is (generation/embedding is stateless server-side), but callers
# composing non-idempotent endpoints must pass idempotent=False.
SHED_RETRIES = 3
SHED_MAX_SLEEP_S = 10.0
RETRY_BACKOFF_BASE_S = 0.25

# what counts as "the connection failed before/without a response" (safe to
# retry an idempotent request): aiohttp's client connection errors, bare OS
# connection resets (also what the fault injector raises), and timeouts
CONNECTION_ERRORS = (
    aiohttp.ClientConnectionError,
    ConnectionError,
    asyncio.TimeoutError,
    TimeoutError,
)


def parse_retry_after(value: Optional[str]) -> Optional[float]:
    """``Retry-After`` per RFC 9110 §10.2.3: delay-seconds OR an HTTP-date.
    Returns seconds from now (>= 0), or None when absent/unparseable."""
    if not value:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        pass
    try:
        dt = email.utils.parsedate_to_datetime(value)
    except (TypeError, ValueError):
        return None
    if dt is None:
        return None
    if dt.tzinfo is None:  # RFC 9110 dates are GMT; be lenient about parsers
        dt = dt.replace(tzinfo=datetime.timezone.utc)
    return max(0.0, (dt - datetime.datetime.now(datetime.timezone.utc)).total_seconds())


def _backoff_s(attempt: int) -> float:
    """Capped jittered exponential backoff (full jitter: 50-100% of the cap
    for this attempt) — retries from many clients must not synchronize."""
    cap = min(SHED_MAX_SLEEP_S, RETRY_BACKOFF_BASE_S * (2**attempt))
    return cap * (0.5 + 0.5 * random.random())


def _fault_injector():
    """The chaos-plane injector, WITHOUT importing the (jax-heavy) serving
    package into processes that only speak HTTP: consult it when the faults
    module is already loaded (a chaos test set one) or the env gate is set."""
    import os
    import sys

    mod = sys.modules.get("django_assistant_bot_tpu.serving.faults")
    if mod is not None:
        return mod.global_injector()
    if os.environ.get("DABT_FAULTS", "").strip():
        from ...serving.faults import global_injector

        return global_injector()
    return None


async def _iter_sse_lines(content):
    """Split an SSE body into lines WITHOUT aiohttp's readline (its 64 KiB
    line cap would reject the terminal event, which carries the whole result
    text in one ``data:`` line on long generations)."""
    buf = b""
    async for chunk in content.iter_any():
        buf += chunk
        while b"\n" in buf:
            raw, buf = buf.split(b"\n", 1)
            yield raw.decode("utf-8", errors="replace").strip()
    if buf:
        yield buf.decode("utf-8", errors="replace").strip()


def _new_request_id() -> str:
    """Wire-format twin of serving.obs.new_trace_id — duplicated here so HTTP
    client processes never import the jax-heavy serving package (the same
    discipline as `_fault_injector` above)."""
    return uuid.uuid4().hex[:16]


async def _post_with_shed_retry(
    session, url: str, payload: dict, *, idempotent: bool = True, headers=None
):
    """POST with the bounded retry policy.

    - **429** (scheduler load shed) always retries, honoring ``Retry-After``
      (delay-seconds or HTTP-date per RFC 9110), capped.
    - **503** (engine degraded — the restart circuit) and **connection
      errors/timeouts** retry only when ``idempotent`` (a connection error
      leaves "did it execute?" unknown), with capped jittered backoff; a 503's
      ``Retry-After`` wins over the computed backoff.
    - Everything else raises immediately; a still-failing server surfaces its
      final error to the caller after ``SHED_RETRIES`` retries.

    ``headers`` ride on every attempt unchanged — the caller's
    ``X-Request-Id`` stays constant across shed retries, so a 429 and the
    retry that follows it correlate server-side by one trace id.
    """
    inj = _fault_injector()
    for attempt in range(SHED_RETRIES + 1):
        last = attempt == SHED_RETRIES
        try:
            if inj is not None:
                # chaos plane: injected timeout/conn_reset/http_5xx exercise
                # this very retry policy without a misbehaving server
                inj.raise_http_fault(url)
            resp = await session.post(url, json=payload, headers=headers)
        except aiohttp.ClientResponseError as e:
            # a response-shaped failure (incl. the injector's http_5xx);
            # the server's Retry-After still wins over the computed backoff
            if e.status not in (429, 503) or (e.status == 503 and not idempotent) or last:
                raise
            retry_after = parse_retry_after(
                e.headers.get("Retry-After") if e.headers else None
            )
            delay = min(
                SHED_MAX_SLEEP_S,
                retry_after if retry_after is not None else _backoff_s(attempt),
            )
            logger.info(
                "%s failed with %d; retrying in %.1fs (%d/%d)",
                url, e.status, delay, attempt + 1, SHED_RETRIES,
            )
            await asyncio.sleep(delay)
            continue
        except CONNECTION_ERRORS as e:
            if not idempotent or last:
                raise
            delay = _backoff_s(attempt)
            logger.info(
                "%s connection failed (%s: %s); retrying in %.1fs (%d/%d)",
                url, type(e).__name__, e, delay, attempt + 1, SHED_RETRIES,
            )
            await asyncio.sleep(delay)
            continue
        retriable = resp.status == 429 or (resp.status == 503 and idempotent)
        if not retriable or last:
            resp.raise_for_status()
            return resp
        retry_after = parse_retry_after(resp.headers.get("Retry-After"))
        delay = min(
            SHED_MAX_SLEEP_S,
            retry_after if retry_after is not None else _backoff_s(attempt),
        )
        resp.release()
        logger.info(
            "%s %s the request (%d); retrying in %.1fs (%d/%d)",
            url,
            "shed" if resp.status == 429 else "is degraded",
            resp.status,
            delay, attempt + 1, SHED_RETRIES,
        )
        await asyncio.sleep(delay)
    raise RuntimeError("unreachable")  # pragma: no cover


class GPUServiceProvider(AIProvider):
    def __init__(
        self,
        base_url: str,
        model: str,
        timeout_s: float = 120.0,
        *,
        priority: str = "interactive",
        tenant: str = "default",
        deadline_s: Optional[float] = None,
    ):
        self._base = base_url.rstrip("/")
        self._model = model
        self._priority = priority
        self._tenant = tenant
        self._deadline_s = deadline_s
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self.calls_attempts: List[int] = []
        # the X-Request-Id of the most recent call (observability: callers
        # quote it when reporting a failed turn; the server's trace ring and
        # flight-recorder events carry the same id)
        self.last_request_id: Optional[str] = None

    @property
    def context_size(self) -> int:
        return 8000  # reference hardcodes this (assistant/ai/providers/openai.py:22)

    def calculate_tokens(self, text: str) -> int:
        return approx_tokens(text)

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        self.calls_attempts.append(1)
        payload = {
            "model": self._model,
            "messages": list(messages),
            "max_tokens": max_tokens,
            "json_format": json_format,
            "priority": self._priority,
            "tenant": self._tenant,
        }
        if self._deadline_s is not None:
            payload["deadline_s"] = self._deadline_s
        # one trace id per logical call, constant across shed retries; the
        # server echoes it on every response shape (and uses it as the
        # engine-side trace_id), so client and server logs correlate
        rid = _new_request_id()
        self.last_request_id = rid
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with await _post_with_shed_retry(
                session,
                f"{self._base}/dialog/",
                payload,
                headers={"X-Request-Id": rid},
            ) as resp:
                data = await resp.json()
                echoed = resp.headers.get("X-Request-Id")
                if echoed and echoed != rid:  # pragma: no cover - server bug
                    logger.warning(
                        "X-Request-Id mismatch: sent %s, got %s", rid, echoed
                    )
        body = data["response"]
        result = body["result"]
        if json_format and isinstance(result, str):
            parsed, _ = parse_json_response(result)
            result = parsed if parsed is not None else {}
        return AIResponse(
            result=result,
            usage=body.get("usage"),
            length_limited=body.get("length_limited", False),
        )

    async def stream_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ):
        """Consume the server's ``text/event-stream`` wire format
        (docs/STREAMING.md): per-delta ``data:`` events, a terminal event with
        usage + the authoritative full text, then ``[DONE]``.  The server
        rejects ``stream`` + ``json_format`` (422), so JSON requests buffer
        through the base adapter here."""
        if json_format:
            async for chunk in AIProvider.stream_response(
                self, messages, max_tokens=max_tokens, json_format=True
            ):
                yield chunk
            return
        self.calls_attempts.append(1)
        payload = {
            "model": self._model,
            "messages": list(messages),
            "max_tokens": max_tokens,
            "json_format": False,
            "stream": True,
            "priority": self._priority,
            "tenant": self._tenant,
        }
        if self._deadline_s is not None:
            payload["deadline_s"] = self._deadline_s
        rid = _new_request_id()
        self.last_request_id = rid
        acc: List[str] = []
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with await _post_with_shed_retry(
                session,
                f"{self._base}/dialog/",
                payload,
                headers={"X-Request-Id": rid},
            ) as resp:
                async for line in _iter_sse_lines(resp.content):
                    if not line.startswith("data:"):
                        continue
                    data = line[len("data:"):].strip()
                    if data == "[DONE]":
                        break
                    event = json.loads(data)
                    if event.get("done"):
                        if event.get("finish_reason") == "error":
                            raise RuntimeError(
                                f"stream failed mid-generation: "
                                f"{event.get('error', 'unknown error')}"
                            )
                        result = event.get("result")
                        yield AIStreamChunk(
                            done=True,
                            response=AIResponse(
                                result="".join(acc) if result is None else result,
                                usage=event.get("usage"),
                                length_limited=event.get("length_limited", False),
                            ),
                        )
                        return
                    delta = event.get("delta", "")
                    if delta:
                        acc.append(delta)
                        yield AIStreamChunk(delta=delta)
        # stream closed without a terminal event (server died mid-stream):
        # surface what arrived rather than silently dropping the turn
        yield AIStreamChunk(
            done=True,
            response=AIResponse(result="".join(acc), usage=None, length_limited=False),
        )


class GPUServiceEmbedder(AIEmbedder):
    def __init__(self, base_url: str, model: str, timeout_s: float = 120.0):
        self._base = base_url.rstrip("/")
        self._model = model
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        payload = {"model": self._model, "texts": list(input)}
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with await _post_with_shed_retry(
                session, f"{self._base}/embeddings/", payload
            ) as resp:
                data = await resp.json()
        return data["embeddings"]
