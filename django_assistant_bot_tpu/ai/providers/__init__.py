from .base import AIDebugger, AIEmbedder, AIProvider  # noqa: F401
