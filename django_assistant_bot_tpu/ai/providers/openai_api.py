"""OpenAI-compatible chat providers: OpenAI and Groq.

The reference uses the vendor SDKs (assistant/ai/providers/openai.py:13-63,
groq.py:18-132); neither SDK is in this image, so both speak the
``/chat/completions`` REST contract directly via aiohttp.  Groq keeps the
reference's extra behaviors: 2-second throttle and JSON-retry.
"""

from __future__ import annotations

from typing import List, Optional

import aiohttp

from ...utils.repeat_until import RepeatUntilError, repeat_until
from ...utils.throttle import Throttle
from ..domain import AIResponse, Message
from .base import AIEmbedder, AIProvider, approx_tokens, parse_json_response


class OpenAICompatProvider(AIProvider):
    throttle_name: Optional[str] = None
    throttle_period_s: float = 0.0

    def __init__(self, model: str, api_key: Optional[str], base_url: str, timeout_s: float = 120.0):
        self._model = model
        self._api_key = api_key
        self._base = base_url.rstrip("/")
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self.calls_attempts: List[int] = []

    @property
    def context_size(self) -> int:
        return 8000  # reference parity (assistant/ai/providers/openai.py:22-23)

    def calculate_tokens(self, text: str) -> int:
        return approx_tokens(text)

    async def _chat(self, messages: List[Message], max_tokens: int, json_format: bool) -> AIResponse:
        payload = {
            "model": self._model,
            "messages": list(messages),
            "max_tokens": max_tokens,
        }
        if json_format:
            payload["response_format"] = {"type": "json_object"}
        headers = {"Authorization": f"Bearer {self._api_key}"} if self._api_key else {}

        async def post():
            async with aiohttp.ClientSession(timeout=self._timeout) as session:
                async with session.post(
                    f"{self._base}/chat/completions", json=payload, headers=headers
                ) as resp:
                    resp.raise_for_status()
                    return await resp.json()

        if self.throttle_name:
            async with Throttle.get(self.throttle_name, self.throttle_period_s):
                data = await post()
        else:
            data = await post()
        choice = data["choices"][0]
        text = choice["message"]["content"]
        usage = dict(data.get("usage") or {})
        usage["model"] = self._model
        return AIResponse(
            result=text,
            usage=usage,
            length_limited=choice.get("finish_reason") == "length",
        )

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        attempts = 0

        async def call() -> AIResponse:
            nonlocal attempts
            attempts += 1
            return await self._chat(messages, max_tokens, json_format)

        if not json_format:
            resp = await call()
            self.calls_attempts.append(attempts)
            return resp

        def valid(resp: AIResponse):
            parsed, err = parse_json_response(resp.result)
            if err:
                return err
            resp.result = parsed
            return True

        try:
            resp = await repeat_until(call, condition=valid, max_attempts=5)
        except RepeatUntilError as e:
            resp = e.last_result
            resp.result = {}
        self.calls_attempts.append(attempts)
        return resp


class ChatGPTAIProvider(OpenAICompatProvider):
    pass


class GroqAIProvider(OpenAICompatProvider):
    throttle_name = "groq"
    throttle_period_s = 2.0  # reference: assistant/ai/providers/groq.py:24


class OpenAIEmbedder(AIEmbedder):
    """text-embedding-3* via /embeddings (reference: assistant/ai/embedders/openai.py)."""

    def __init__(self, model: str, api_key: Optional[str], base_url: str, timeout_s: float = 120.0):
        self._model = model
        self._api_key = api_key
        self._base = base_url.rstrip("/")
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        headers = {"Authorization": f"Bearer {self._api_key}"} if self._api_key else {}
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with session.post(
                f"{self._base}/embeddings",
                json={"model": self._model, "input": list(input)},
                headers=headers,
            ) as resp:
                resp.raise_for_status()
                data = await resp.json()
        return [d["embedding"] for d in data["data"]]
