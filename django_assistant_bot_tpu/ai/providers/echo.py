"""Deterministic test provider/embedder (the reference's DEFAULT_AI_MODEL='test'
strategy — tests/settings.py:132 — made executable).

``EchoProvider`` answers with a canned or scripted response; ``HashEmbedder``
maps text to a stable pseudo-random unit vector (same text -> same vector, so
KNN behavior is deterministic in tests without any model).
"""

from __future__ import annotations

import hashlib
import json
import re
from typing import List, Optional, Sequence

import numpy as np

from ..domain import AIResponse, Message
from .base import AIEmbedder, AIProvider, AIStreamChunk


class EchoProvider(AIProvider):
    """Scripted responses: pop from ``script`` if set, else echo the last user
    message.  ``json_format=True`` returns the scripted dict or ``{"echo": ...}``."""

    def __init__(self, model: str = "test", script: Optional[Sequence] = None):
        self._model = model
        self.script: List = list(script or [])
        self.history: List[List[Message]] = []
        self.calls_attempts: List[int] = []

    @property
    def context_size(self) -> int:
        return 8000

    def calculate_tokens(self, text: str) -> int:
        return max(1, len(text.split()) // 2) if text else 0

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        self.calls_attempts.append(1)
        self.history.append(list(messages))
        usage = {
            "model": self._model,
            "prompt_tokens": sum(self.calculate_tokens(m["content"]) for m in messages),
            "completion_tokens": 1,
            "total_tokens": 1,
        }
        if self.script:
            item = self.script.pop(0)
            if isinstance(item, AIResponse):
                return item
            if isinstance(item, dict) and not json_format:
                return AIResponse(result=json.dumps(item), usage=usage)
            return AIResponse(result=item, usage=usage)
        last_user = next(
            (m["content"] for m in reversed(messages) if m["role"] == "user"), ""
        )
        if json_format:
            return AIResponse(result={"echo": last_user}, usage=usage)
        return AIResponse(result=f"echo: {last_user}", usage=usage)

    async def stream_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ):
        """Deterministic word-by-word stream for tests: the scripted/echoed
        text split into word+whitespace pieces whose concatenation is
        byte-identical to the ``get_response`` result."""
        resp = await self.get_response(
            messages, max_tokens=max_tokens, json_format=json_format
        )
        text = (
            resp.result
            if isinstance(resp.result, str)
            else json.dumps(resp.result, ensure_ascii=False)
        )
        # lossless partition: non-space runs keep their trailing whitespace;
        # a leading whitespace run is its own piece
        for piece in re.findall(r"\S+\s*|\s+", text):
            yield AIStreamChunk(delta=piece)
        yield AIStreamChunk(done=True, response=resp)


class HashEmbedder(AIEmbedder):
    def __init__(self, dim: int = 768):
        self.dim = dim

    def _vec(self, text: str) -> List[float]:
        seed = int.from_bytes(hashlib.sha256(text.encode()).digest()[:8], "little")
        rng = np.random.default_rng(seed)
        v = rng.normal(size=self.dim).astype(np.float32)
        v /= np.linalg.norm(v)
        return v.tolist()

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        return [self._vec(t) for t in input]
