"""Ollama provider/embedder via the Ollama REST API.

The reference uses the ollama SDK with a 5-attempt JSON-repair retry loop and a
same-role merge guard (assistant/ai/providers/ollama.py:49-107); this speaks
``/api/chat`` and ``/api/embeddings`` directly and keeps both behaviors.
"""

from __future__ import annotations

from typing import List

import aiohttp

from ...utils.repeat_until import RepeatUntilError, repeat_until
from ..domain import AIResponse, Message
from .base import AIEmbedder, AIProvider, approx_tokens, parse_json_response


def merge_same_roles(messages: List[Message]) -> List[Message]:
    """Ollama rejects consecutive same-role messages; merge them."""
    out: List[Message] = []
    for m in messages:
        if out and out[-1]["role"] == m["role"]:
            out[-1] = Message(
                role=m["role"], content=out[-1]["content"] + "\n" + m["content"]
            )
        else:
            out.append(dict(m))  # type: ignore[arg-type]
    return out


class OllamaAIProvider(AIProvider):
    def __init__(self, model: str, host: str, timeout_s: float = 300.0):
        self._model = model
        self._host = host.rstrip("/")
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)
        self.calls_attempts: List[int] = []

    @property
    def context_size(self) -> int:
        return 8000  # reference parity (assistant/ai/providers/ollama.py:29-30)

    def calculate_tokens(self, text: str) -> int:
        return approx_tokens(text)

    async def _chat(self, messages: List[Message], max_tokens: int, json_format: bool):
        payload = {
            "model": self._model,
            "messages": merge_same_roles(messages),
            "stream": False,
            "options": {"num_predict": max_tokens},
        }
        if json_format:
            payload["format"] = "json"
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            async with session.post(f"{self._host}/api/chat", json=payload) as resp:
                resp.raise_for_status()
                return await resp.json()

    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse:
        attempts = 0

        async def call() -> AIResponse:
            nonlocal attempts
            attempts += 1
            data = await self._chat(messages, max_tokens, json_format)
            text = data.get("message", {}).get("content", "")
            usage = {
                "model": self._model,
                "prompt_tokens": data.get("prompt_eval_count", 0),
                "completion_tokens": data.get("eval_count", 0),
            }
            usage["total_tokens"] = usage["prompt_tokens"] + usage["completion_tokens"]
            return AIResponse(
                result=text,
                usage=usage,
                length_limited=data.get("done_reason") == "length",
            )

        if not json_format:
            resp = await call()
            self.calls_attempts.append(attempts)
            return resp

        def valid(resp: AIResponse):
            parsed, err = parse_json_response(resp.result)
            if err:
                return err
            resp.result = parsed
            return True

        try:
            resp = await repeat_until(call, condition=valid, max_attempts=5)
        except RepeatUntilError as e:
            resp = e.last_result
            resp.result = {}
        self.calls_attempts.append(attempts)
        return resp


class OllamaEmbedder(AIEmbedder):
    def __init__(self, model: str, host: str, timeout_s: float = 300.0):
        self._model = model
        self._host = host.rstrip("/")
        self._timeout = aiohttp.ClientTimeout(total=timeout_s)

    async def embeddings(self, input: List[str]) -> List[List[float]]:
        out: List[List[float]] = []
        async with aiohttp.ClientSession(timeout=self._timeout) as session:
            for text in input:  # per-text loop = reference behavior (embedders/ollama.py:8-23)
                async with session.post(
                    f"{self._host}/api/embeddings",
                    json={"model": self._model, "prompt": text},
                ) as resp:
                    resp.raise_for_status()
                    data = await resp.json()
                out.append(data["embedding"])
        return out
