"""Provider/embedder ABCs + AIDebugger (reference: assistant/ai/providers/base.py:8-71).

Also home to the shared JSON-repair helper every provider uses for
``json_format=True``: parse, strip code fences, retry-worthy error reporting —
the reference implements this per-provider (ollama.py:49-107, groq.py:53-92); here
it is one code path.
"""

from __future__ import annotations

import dataclasses
import json
import re
from abc import ABC, abstractmethod
from typing import Any, AsyncIterator, Dict, List, Optional, Tuple

from ...utils.debug import TimeDebugger
from ..domain import AIResponse, Message


@dataclasses.dataclass
class AIStreamChunk:
    """One provider-level streaming event: a text ``delta``, or the terminal
    chunk (``done=True``) carrying the full :class:`AIResponse` — whose
    ``result`` equals the concatenation of every delta for natively-streaming
    providers, and is the authoritative value either way."""

    delta: str = ""
    done: bool = False
    response: Optional[AIResponse] = None


class AIProvider(ABC):
    calls_attempts: List[int]

    @property
    @abstractmethod
    def context_size(self) -> int: ...

    @abstractmethod
    def calculate_tokens(self, text: str) -> int: ...

    @abstractmethod
    async def get_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AIResponse: ...

    async def stream_response(
        self,
        messages: List[Message],
        max_tokens: int = 1024,
        json_format: bool = False,
    ) -> AsyncIterator[AIStreamChunk]:
        """Async iterator of :class:`AIStreamChunk`: text deltas, then one
        terminal chunk with the full :class:`AIResponse`.

        Default adapter: buffer the whole :meth:`get_response` result and
        yield it as a single delta — every existing provider (OpenAI, Ollama,
        Groq, Echo scripts) streams correctly with zero changes, just without
        progressive output.  Providers with a native token stream (TPU
        in-process, gpu_service SSE) override this."""
        resp = await self.get_response(
            messages, max_tokens=max_tokens, json_format=json_format
        )
        text = (
            resp.result
            if isinstance(resp.result, str)
            else json.dumps(resp.result, ensure_ascii=False)
        )
        if text:
            yield AIStreamChunk(delta=text)
        yield AIStreamChunk(done=True, response=resp)


class AIEmbedder(ABC):
    @abstractmethod
    async def embeddings(self, input: List[str]) -> List[List[float]]: ...


class AIDebugger(TimeDebugger):
    """Timing + attempt-count + model-name recorder around one provider call
    (reference: assistant/ai/providers/base.py:48-71)."""

    def __init__(self, ai: AIProvider, debug_info: Optional[Dict[str, Any]], key: str):
        super().__init__(debug_info, key)
        self.ai = ai

    def __enter__(self) -> "AIDebugger":
        self.ai.calls_attempts = []
        return super().__enter__()  # type: ignore[return-value]

    def __exit__(self, *exc) -> None:
        super().__exit__(*exc)
        attempts = getattr(self.ai, "calls_attempts", None)
        self.node["attempts"] = sum(attempts) if attempts else None
        self.node["model"] = getattr(self.ai, "_model", None)


_FENCE_RE = re.compile(r"```(?:json)?\s*(.*?)\s*```", re.DOTALL)


def parse_json_response(text: str) -> Tuple[Optional[Dict], Optional[str]]:
    """Best-effort JSON extraction: direct parse, fenced block, first {...} span.

    Returns (parsed, error).  ``error`` is a human-readable reason used by
    retry loops when parsing fails.
    """
    if isinstance(text, dict):
        return text, None
    candidates = [text]
    m = _FENCE_RE.search(text)
    if m:
        candidates.append(m.group(1))
    start, end = text.find("{"), text.rfind("}")
    if start != -1 and end > start:
        candidates.append(text[start : end + 1])
    for cand in candidates:
        try:
            parsed = json.loads(cand)
            if isinstance(parsed, dict):
                return parsed, None
        except (json.JSONDecodeError, TypeError):
            continue
    return None, f"no valid JSON object in response ({text[:80]!r}...)"


def approx_tokens(text: str) -> int:
    """The reference's heuristic token count (len(split)//2 — openai.py:26)."""
    return max(1, len(text.split()) // 2) if text else 0
