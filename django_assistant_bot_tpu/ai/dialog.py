"""AIDialog — one-model conversation helper (reference: assistant/ai/dialog.py:11-45)."""

from __future__ import annotations

from typing import List

from .domain import AIResponse, Message
from .providers.base import AIProvider
from .services.ai_service import get_ai_provider


class AIDialog(AIProvider):
    def __init__(self, model: str, *, priority: str = "interactive", tenant: str = "default"):
        self._model = model
        self._provider = get_ai_provider(model, priority=priority, tenant=tenant)

    async def prompt(self, context: str, role: str = "user", **kwargs) -> AIResponse:
        return await self._provider.get_response(
            messages=[Message(role=role, content=context)], **kwargs
        )

    @property
    def calls_attempts(self):
        return self._provider.calls_attempts

    @calls_attempts.setter
    def calls_attempts(self, value):
        self._provider.calls_attempts = value

    @property
    def context_size(self) -> int:
        return self._provider.context_size

    def calculate_tokens(self, text: str) -> int:
        return self._provider.calculate_tokens(text)

    async def get_response(
        self, messages: List[Message], max_tokens: int = 1024, json_format: bool = False
    ) -> AIResponse:
        return await self._provider.get_response(messages, max_tokens, json_format)

    def stream_response(
        self, messages: List[Message], max_tokens: int = 1024, json_format: bool = False
    ):
        # returns the provider's async iterator directly (native streams keep
        # streaming; others get the buffered default adapter)
        return self._provider.stream_response(
            messages, max_tokens=max_tokens, json_format=json_format
        )
