"""Prefix-routed provider/embedder factories + cost/usage accounting.

Routing parity with the reference (assistant/ai/services/ai_service.py:14-74) plus
the new ``tpu:`` prefix and a ``test`` model for deterministic tests:

providers: ``failover:<m>|<m>|...`` (ordered chain with per-backend circuit
breakers) | ``tpu:`` | ``groq:`` | ``gpu_service:`` | ``ollama:``/``llama*`` |
``test`` | else OpenAI.
embedders: ``tpu:`` | ``text-embedding-3*`` -> OpenAI | ``gpu_service:`` |
``test`` | else Ollama.
"""

from __future__ import annotations

import logging
import re
from typing import Dict, Optional

from ...conf import settings
from ..providers.base import AIEmbedder, AIProvider

logger = logging.getLogger(__name__)


def get_ai_provider(
    model: str,
    *,
    priority: str = "interactive",
    tenant: str = "default",
    deadline_s: Optional[float] = None,
) -> AIProvider:
    """``priority``/``tenant``/``deadline_s`` tag requests for the serving
    scheduler (serving/scheduler.py): interactive dialog turns outrank
    background ingestion.  Providers without a scheduling plane (OpenAI,
    Ollama, ...) simply ignore the tags."""
    logger.debug("getting AI provider for model %s", model)
    if model.startswith("failover:"):
        # ordered chain: "failover:tpu:chat|gpu_service:chat|test" — each leg
        # is routed by this same factory; a per-backend circuit breaker skips
        # legs that keep failing (ai/providers/failover.py, docs/RESILIENCE.md)
        from ..providers.failover import FailoverProvider

        chain = [m.strip() for m in model[len("failover:"):].split("|") if m.strip()]
        if not chain:
            raise ValueError("failover: model needs at least one backend, "
                             "e.g. failover:tpu:chat|test")
        return FailoverProvider(
            [
                get_ai_provider(
                    m, priority=priority, tenant=tenant, deadline_s=deadline_s
                )
                for m in chain
            ],
            names=chain,
        )
    if model.startswith("tpu:"):
        from ..providers.tpu import TPUProvider

        return TPUProvider(
            model[len("tpu:"):],
            priority=priority,
            tenant=tenant,
            deadline_s=deadline_s,
        )
    if model.startswith("groq:"):
        from ..providers.openai_api import GroqAIProvider

        return GroqAIProvider(
            model[len("groq:"):],
            api_key=settings.GROQ_API_KEY,
            base_url=settings.GROQ_BASE_URL,
        )
    if model.startswith("gpu_service:"):
        from ..providers.http_service import GPUServiceProvider

        return GPUServiceProvider(
            base_url=settings.GPU_SERVICE_ENDPOINT,
            model=model[len("gpu_service:"):],
            priority=priority,
            tenant=tenant,
            deadline_s=deadline_s,
        )
    if model.startswith("ollama:") or model.startswith("llama"):
        from ..providers.ollama import OllamaAIProvider

        name = model[len("ollama:"):] if model.startswith("ollama:") else model
        return OllamaAIProvider(model=name, host=settings.OLLAMA_ENDPOINT)
    if model == "test" or model.startswith("test:"):
        from ..providers.echo import EchoProvider

        return EchoProvider(model)
    from ..providers.openai_api import ChatGPTAIProvider

    return ChatGPTAIProvider(
        model, api_key=settings.OPENAI_API_KEY, base_url=settings.OPENAI_BASE_URL
    )


def get_ai_embedder(model: Optional[str] = None) -> AIEmbedder:
    if not model:
        model = "nomic-embed-text"
    if model.startswith("tpu:"):
        from ..providers.tpu import TPUEmbedder

        return TPUEmbedder(model[len("tpu:"):])
    if model.startswith("text-embedding-3"):
        from ..providers.openai_api import OpenAIEmbedder

        return OpenAIEmbedder(
            model, api_key=settings.OPENAI_API_KEY, base_url=settings.OPENAI_BASE_URL
        )
    if model.startswith("gpu_service:"):
        from ..providers.http_service import GPUServiceEmbedder

        return GPUServiceEmbedder(
            base_url=settings.GPU_SERVICE_ENDPOINT, model=model[len("gpu_service:"):]
        )
    if model == "test" or model.startswith("test:"):
        from ..providers.echo import HashEmbedder

        # match the storage schema's vector width so test vectors round-trip
        return HashEmbedder(dim=settings.EMBEDDING_DIM)
    from ..providers.ollama import OllamaEmbedder

    return OllamaEmbedder(model=model, host=settings.OLLAMA_ENDPOINT)


# Backwards-compatible alias: the reference misspells this factory
# (assistant/ai/services/ai_service.py:51 `get_ai_embdedder`).
get_ai_embdedder = get_ai_embedder


def extract_tagged_text(text: str) -> Dict[str, str]:
    """``#tag content`` sections -> {tag: content} (reference ai_service.py:77-86)."""
    pattern = r"#(\w+)\s?(.*?)(?=\s#|$)"
    matches = re.findall(pattern, text or "", re.DOTALL)
    return {tag.lower(): body.strip() for tag, body in matches}


# $/1K tokens (prompt, completion) — reference table: ai_service.py:89-122,
# extended with current OpenAI models; tpu/local models cost 0.
_COST_PER_1K: Dict[str, tuple] = {
    "gpt-3.5-turbo": (0.001, 0.002),
    "gpt-4": (0.03, 0.06),  # plain gpt-4 (longest-prefix match keeps this last)
    "gpt-4-": (0.01, 0.03),
    "gpt-4o-mini": (0.00015, 0.0006),
    "gpt-4o": (0.0025, 0.01),
}


def calculate_ai_cost(usage: Dict) -> float:
    model = usage.get("model") or ""
    prompt = usage.get("prompt_tokens", 0) or 0
    completion = usage.get("completion_tokens", 0) or 0
    for prefix, (p_in, p_out) in sorted(
        _COST_PER_1K.items(), key=lambda kv: -len(kv[0])
    ):
        if model.startswith(prefix):
            return (prompt * p_in + completion * p_out) / 1000.0
    # Anything else is a locally-served model (tpu/ollama/gpu_service providers
    # strip their routing prefix before writing usage["model"]) — cost 0.
    logger.debug("model %s not in cost table; charging 0", model)
    return 0.0
