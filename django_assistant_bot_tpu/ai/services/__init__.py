from .ai_service import (  # noqa: F401
    calculate_ai_cost,
    extract_tagged_text,
    get_ai_embedder,
    get_ai_provider,
)
