"""AI domain types (reference: assistant/ai/domain.py:5-30)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, TypedDict, Union


@dataclass
class AIResponse:
    result: Union[str, Dict]  # str, or dict when json_format=True
    usage: Optional[Dict] = field(default=None)
    length_limited: bool = False

    @property
    def model(self) -> Optional[str]:
        return self.usage.get("model") if self.usage else None


class Message(TypedDict):
    role: str
    content: str


def user_message(content: str) -> Message:
    return Message(role="user", content=content)


def assistant_message(content: str) -> Message:
    return Message(role="assistant", content=content)


def system_message(content: str) -> Message:
    return Message(role="system", content=content)
