"""Settings — the django-environ replacement (reference flag surface: SURVEY.md §5.6).

Every flag the reference reads from Django settings/.env exists here, read from
``DABT_*`` environment variables with the same semantics: per-role model selection,
backend endpoints, resource dirs, and the ``BOTS`` registry mapping codenames to
bot classes + platform tokens.  ``settings.override(...)`` is the test hook.

Model-string prefix routing doubles as provider selection exactly like the
reference (reference: assistant/ai/services/ai_service.py:14-74): ``tpu:`` (new,
in-process TPU serving), ``gpu_service:`` (HTTP to a gpu_service-contract server —
including our own), ``groq:``, ``ollama:``/``llama*``, ``test``, else OpenAI.
"""

from __future__ import annotations

import contextlib
import importlib
import os
from typing import Any, Dict, Iterator, Optional


def _env(name: str, default: Any = None) -> Any:
    return os.environ.get(f"DABT_{name}", os.environ.get(name, default))


class Settings:
    def __init__(self) -> None:
        self.reload()

    def reload(self) -> None:
        # per-role model selection (reference: .env.example:12-19)
        self.DEFAULT_AI_MODEL: str = _env("DEFAULT_AI_MODEL", "test")
        self.EMBEDDING_AI_MODEL: str = _env("EMBEDDING_AI_MODEL", "test")
        self.DIALOG_FAST_AI_MODEL: str = _env("DIALOG_FAST_AI_MODEL", self.DEFAULT_AI_MODEL)
        self.DIALOG_STRONG_AI_MODEL: str = _env("DIALOG_STRONG_AI_MODEL", self.DEFAULT_AI_MODEL)
        self.SPLIT_AI_MODEL: str = _env("SPLIT_AI_MODEL", self.DEFAULT_AI_MODEL)
        self.FORMAT_AI_MODEL: str = _env("FORMAT_AI_MODEL", self.DEFAULT_AI_MODEL)
        self.SENTENCES_AI_MODEL: str = _env("SENTENCES_AI_MODEL", self.DEFAULT_AI_MODEL)
        self.QUESTIONS_AI_MODEL: str = _env("QUESTIONS_AI_MODEL", self.DEFAULT_AI_MODEL)
        # backend endpoints
        self.OLLAMA_ENDPOINT: str = _env("OLLAMA_ENDPOINT", "http://localhost:11434")
        self.GPU_SERVICE_ENDPOINT: str = _env("GPU_SERVICE_ENDPOINT", "http://localhost:11435")
        self.OPENAI_API_KEY: Optional[str] = _env("OPENAI_API_KEY")
        self.OPENAI_BASE_URL: str = _env("OPENAI_BASE_URL", "https://api.openai.com/v1")
        self.GROQ_API_KEY: Optional[str] = _env("GROQ_API_KEY")
        self.GROQ_BASE_URL: str = _env("GROQ_BASE_URL", "https://api.groq.com/openai/v1")
        # resources + registries
        self.RESOURCES_DIR: Optional[str] = _env("RESOURCES_DIR")
        # fallback language for messages/phrases (reference:
        # settings.BOT_DEFAULT_LANGUAGE, assistant/bot/resource_manager.py:14)
        self.BOT_DEFAULT_LANGUAGE: str = _env("BOT_DEFAULT_LANGUAGE", "ru")
        self.API_AUTH_TOKEN: Optional[str] = _env("API_AUTH_TOKEN")
        # "user:password" protecting /admin with HTTP Basic; falls back to
        # "admin:<API_AUTH_TOKEN>" when only the token is configured
        self.ADMIN_BASIC_AUTH: Optional[str] = _env("ADMIN_BASIC_AUTH")
        self.WEBHOOK_BASE_URL: Optional[str] = _env("WEBHOOK_BASE_URL")
        # sent to Telegram at setWebhook and required back on every webhook
        # delivery via X-Telegram-Bot-Api-Secret-Token
        self.TELEGRAM_WEBHOOK_SECRET: Optional[str] = _env("TELEGRAM_WEBHOOK_SECRET")
        self.BOTS: Dict[str, Dict[str, Any]] = {}
        # TPU serving config (model registry TOML/JSON path for the `tpu:` provider)
        self.TPU_SERVING_CONFIG: Optional[str] = _env("TPU_SERVING_CONFIG")
        # ingestion plane
        self.DOCUMENT_MAX_LENGTH: int = int(_env("DOCUMENT_MAX_LENGTH", 1000))
        # None -> derive the expected language per document from its source text;
        # the reference hardcodes 'ru' in its repeat_until conditions
        self.DOCUMENT_LANGUAGE: Optional[str] = _env("DOCUMENT_LANGUAGE")
        self.DOCUMENT_PROCESSOR_CLASSES: Dict[str, str] = {}
        # task plane
        self.TASK_DB_PATH: Optional[str] = _env("TASK_DB_PATH")
        self.TASK_ALWAYS_EAGER: bool = str(_env("TASK_ALWAYS_EAGER", "0")) in ("1", "true", "True")
        # dialog lifecycle
        self.DIALOG_TTL_S: int = int(_env("DIALOG_TTL_S", 24 * 3600))
        # progressive answer delivery: post the first streamed chunk early and
        # edit-update it (platforms with edit support only; Telegram edits are
        # throttled to >= STREAM_EDIT_INTERVAL_S apart, final edit always
        # sent).  Off by default: whole-message delivery is the reference
        # behavior and the non-streaming bench baseline.
        self.STREAM_BOT_ANSWERS: bool = str(_env("STREAM_BOT_ANSWERS", "0")) in (
            "1", "true", "True",
        )
        self.STREAM_EDIT_INTERVAL_S: float = float(_env("STREAM_EDIT_INTERVAL_S", 1.0))
        # vector schema (reference fixes 768 for ruBert — assistant/storage/models.py:13;
        # configurable here so tiny dev models and other embedders fit the same schema)
        self.EMBEDDING_DIM: int = int(_env("EMBEDDING_DIM", 768))
        # shard RAG vector indexes over the mesh `data` axis (storage/knn.py
        # sharded variant): corpora beyond one chip's HBM score shard-locally
        # with an all-gather top-k merge.  Off by default — single-chip
        # deployments replicate-free either way.
        self.KNN_MESH: bool = str(_env("DABT_KNN_MESH", "0")) in ("1", "true", "True")
        # ANN retrieval plane (storage/ann.py): corpora at or above
        # ANN_THRESHOLD rows build an IVF-PQ index instead of the exact one.
        # ANN=0 is the one-flag rollback to exact search everywhere.
        self.ANN: bool = str(_env("ANN", "1")) in ("1", "true", "True")
        self.ANN_THRESHOLD: int = int(_env("ANN_THRESHOLD", 200_000))
        # 0 = auto (~2*sqrt(n) lists; nlist/64 probes; dim/8 subquantizers)
        self.ANN_NLIST: int = int(_env("ANN_NLIST", 0))
        self.ANN_M: int = int(_env("ANN_M", 0))
        self.ANN_NPROBE: int = int(_env("ANN_NPROBE", 0))
        self.ANN_RERANK: int = int(_env("ANN_RERANK", 256))
        # durable retrieval plane (storage/durable.py): set a directory and
        # every ANN-routed index gets a WAL + atomic snapshots + per-document
        # idempotency ledger — crash recovery replays to the pre-crash index
        # instead of re-embedding/retraining.  Unset keeps the volatile
        # in-RAM behavior (the DB rebuild is then the only durability).
        self.ANN_DURABLE_DIR: Optional[str] = _env("ANN_DURABLE_DIR")
        # WAL fsync policy: "always" (every record durable before the append
        # returns), "interval" (batched fsync), "never" (page cache decides —
        # bulk-load/bench mode)
        self.ANN_WAL_FSYNC: str = str(_env("ANN_WAL_FSYNC", "always"))
        # auto-snapshot after this many WAL records (0 = manual/CLI only);
        # keep the newest N snapshots on disk
        self.ANN_SNAPSHOT_EVERY: int = int(_env("ANN_SNAPSHOT_EVERY", 512))
        self.ANN_SNAPSHOT_KEEP: int = int(_env("ANN_SNAPSHOT_KEEP", 2))
        # mmap-back the host f32 row tier (corpora past host RAM page from
        # disk; the device bf16 rerank tier stays in HBM)
        self.ANN_MMAP_ROWS: bool = str(_env("ANN_MMAP_ROWS", "0")) in ("1", "true", "True")
        # media plane (reference: settings.MEDIA_URL + MediaURLMiddleware,
        # assistant/assistant/middleware.py:4-15)
        self.MEDIA_URL: str = _env("MEDIA_URL", "/media/")
        self.MEDIA_ROOT: Optional[str] = _env("MEDIA_ROOT")

    def import_string(self, path: str):
        module, _, name = path.rpartition(".")
        return getattr(importlib.import_module(module), name)

    @contextlib.contextmanager
    def override(self, **kw) -> Iterator["Settings"]:
        old = {k: getattr(self, k) for k in kw}
        for k, v in kw.items():
            setattr(self, k, v)
        try:
            yield self
        finally:
            for k, v in old.items():
                setattr(self, k, v)


settings = Settings()
