"""Prompt-lookup speculative decoding: on-device n-gram drafts + acceptance.

The reference's core workload is "answer from the provided context"
(assistant/bot/services/context_service/steps/final_prompt.py packs retrieved
documents into the prompt) — exactly the regime where generated text copies
long spans of the prompt, and where prompt-lookup decoding (PLD: draft the K
tokens that followed the last occurrence of the current n-gram in the
prompt/history, verify all K in ONE forward) multiplies single-stream decode
throughput without any draft model.

TPU-native formulation: both the draft construction and the acceptance rule
are pure static-shape array programs that fuse into the engine's decode tick
— the draft source is a DEVICE-resident token-history buffer, so the whole
speculative step (draft -> verify -> accept -> cache/length update) chains
tick-to-tick on device with zero host round trips.  A host-side draft builder
would cost one tunnel RTT (~90 ms) per tick — more than the tokens it saves.

Greedy rows (temperature <= 0) accept drafts exactly (verified against the
model's own argmax); sampled rows simply take the position-0 token
(n_acc = 0), so mixed batches work and only greedy rows accelerate — the
same scope production PLD implementations choose.

Equivalence guarantee, stated precisely: speculative greedy output equals
non-speculative greedy output in exact arithmetic, and is bit-identical on
the f32 CPU mesh (tested).  On bf16 MXU hardware the 1-token and
(K+1)-token forwards accumulate in different orders, so an argmax decided by
a near-tie (observed delta ~5e-5 at 1B geometry) can break differently —
the same class of divergence that changing the prefill bucket or slot count
already produces.  Within one speculative deployment, decoding is
self-consistent: accepted tokens are exactly what the verify program's
argmax produces.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def build_prompt_lookup_draft(
    history: jnp.ndarray,  # [B, S] int32 token history rows
    lengths: jnp.ndarray,  # [B] cache lengths; history[b, :lengths[b]] is valid
    tokens: jnp.ndarray,  # [B] the pending input token (sequence pos lengths[b])
    k: int,
) -> jnp.ndarray:
    """Draft [B, k]: the tokens that followed the last occurrence of the
    current tail bigram (fallback: unigram) in each row's history.

    Rows with no match draft from position `n` (garbage/stale tokens) — their
    drafts are simply rejected by verification; correctness never depends on
    the draft.  O(B*S) compares — noise next to one decode matmul."""
    B, S = history.shape
    n = lengths + 1  # known sequence tokens incl. the pending input
    js = jnp.arange(S - 1)
    prev = jnp.take_along_axis(
        history, jnp.maximum(lengths - 1, 0)[:, None], axis=1
    )[:, 0]  # token before the pending input
    # bigram (prev, tokens) at (j, j+1), ending strictly before the tail bigram
    big = (history[:, :-1] == prev[:, None]) & (history[:, 1:] == tokens[:, None])
    big = big & ((js[None, :] + 1) < (n - 1)[:, None])
    has2 = big.any(axis=1)
    j2 = jnp.max(jnp.where(big, js[None, :], -1), axis=1)
    # unigram fallback: last occurrence of `tokens` strictly before pos n-1
    jsf = jnp.arange(S)
    uni = (history == tokens[:, None]) & (jsf[None, :] < (n - 1)[:, None])
    has1 = uni.any(axis=1)
    j1 = jnp.max(jnp.where(uni, jsf[None, :], -1), axis=1)
    start = jnp.where(has2, j2 + 2, jnp.where(has1, j1 + 1, n))
    idx = jnp.clip(start[:, None] + jnp.arange(k)[None, :], 0, S - 1)
    return jnp.take_along_axis(history, idx, axis=1)


def accept_drafts(
    logits: jnp.ndarray,  # [B, C, V] f32 — verify logits; C = K+1
    seq: jnp.ndarray,  # [B, C] int32 — col 0 = input token, cols 1..K = drafts
    rng: jax.Array,
    *,
    temperature: jnp.ndarray,  # [B]
    top_k: int,
    top_p: jnp.ndarray,  # [B]
):
    """Longest-prefix greedy acceptance + one bonus/corrected token per row.

    Returns (out [B, C] — out[b, :n_new[b]] are the new sequence tokens,
    n_new [B] in [1, C], bonus [B] — the next tick's input token, rng).

    Greedy rows: draft d_i is accepted iff the model's argmax at the previous
    position equals it AND every earlier draft was accepted; the token after
    the accepted run is the model's own argmax there (exactly what
    non-speculative greedy would have produced — equivalence is testable and
    tested).  Sampled rows accept nothing and sample position 0 with their own
    temperature/top-p, so one compiled program serves mixed batches."""
    from .sampling import sample_logits

    B, C, _ = logits.shape
    greedy_next = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, C]
    rng, sub = jax.random.split(rng)
    samp0 = sample_logits(
        logits[:, 0], sub, temperature=temperature, top_k=top_k, top_p=top_p
    )
    greedy_row = temperature <= 0.0
    match = (greedy_next[:, :-1] == seq[:, 1:]) & greedy_row[:, None]  # [B, K]
    n_acc = jnp.cumprod(match.astype(jnp.int32), axis=1).sum(axis=1)  # leading run
    bonus_greedy = jnp.take_along_axis(greedy_next, n_acc[:, None], axis=1)[:, 0]
    # at temp<=0 sample_logits IS argmax, so samp0 == bonus_greedy when n_acc==0
    bonus = jnp.where(greedy_row, bonus_greedy, samp0)
    js = jnp.arange(C)[None, :]
    accepted = jnp.concatenate(
        [seq[:, 1:], jnp.zeros((B, 1), seq.dtype)], axis=1
    )  # accepted candidate at output index j is seq[:, j+1]
    out = jnp.where(
        js < n_acc[:, None],
        accepted,
        jnp.where(js == n_acc[:, None], bonus[:, None], 0),
    ).astype(jnp.int32)
    n_new = n_acc + 1
    return out, n_new.astype(jnp.int32), bonus.astype(jnp.int32), rng
