"""Tree-verified prompt-lookup speculative decoding: drafts, acceptance, control.

The reference's core workload is "answer from the provided context"
(assistant/bot/services/context_service/steps/final_prompt.py packs retrieved
documents into the prompt) — exactly the regime where generated text copies
long spans of the prompt, and where prompt-lookup decoding (draft the tokens
that followed an occurrence of the current n-gram in the prompt/history,
verify them in ONE forward) multiplies single-stream decode throughput
without any draft model.

This module is the SpecInfer-style generalisation of the original single-
candidate draft: instead of one linear K-token guess, the drafter emits the
top-N DISTINCT continuations (bigram hits ranked by recency, deduplicated on
their first token, unigram fallback) as a static token TREE — a shared root
(the pending input token) plus N linear branches of depth K, flattened into
a fixed ``[B, T]`` layout (T = 1 + N*K) with a precomputed ancestor mask.
One fused verify forward scores every node (positions share the verified
prefix and diverge per branch through the mask), and acceptance takes the
longest root-to-leaf path that matches the model's own argmax.  A single
wrong guess no longer wastes the whole verify tick: any branch can win.

TPU-native formulation: draft construction, verification and acceptance are
pure static-shape array programs that fuse into the engine's decode tick —
the draft source is a DEVICE-resident token-history buffer, so the whole
speculative step chains tick-to-tick on device with zero host round trips.

Greedy rows (temperature <= 0) accept drafts exactly (verified against the
model's own argmax); sampled rows simply take the position-0 token
(n_acc = 0), so mixed batches work and only greedy rows accelerate — the
same scope production PLD implementations choose.

Equivalence guarantee, stated precisely: speculative greedy output equals
non-speculative greedy output in exact arithmetic, and token-identically on
the f32 CPU mesh (property-tested in tests/test_speculative.py across ragged
batches, mixed temperatures and no-match rows).  The bf16 near-tie caveat
(and the jaxlib sequence-sharding pitfall the verify program must avoid)
are documented in docs/SPECULATIVE.md.

The :class:`SpecController` at the bottom is the host-side acceptance-EMA
policy: it shrinks the tree (width, then depth) when measured acceptance
cannot pay for the verify forward, and disables speculation entirely below
the measured verify/decode breakeven — so speculation can never be a
sustained slowdown.  Pure python, unit-testable without a device.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp


class TreeSpec(NamedTuple):
    """Static layout of one speculation tree: a root (flat index 0, the
    pending input token) plus ``width`` linear branches of ``depth`` draft
    tokens.  Node (n, d) lives at flat index ``1 + n*depth + d``.

    All arrays are host-side numpy constants baked into the jitted tick —
    the tree SHAPE never changes inside a compiled program (the adaptive
    controller switches between a small ladder of precompiled shapes).
    """

    width: int
    depth: int
    size: int  # T = 1 + width*depth flattened nodes
    depths: np.ndarray  # [T] int32 — node depth; root = 0
    parent: np.ndarray  # [T] int32 — flat parent index; root's parent = 0
    anc_mask: np.ndarray  # [T, T] bool — anc_mask[t, u]: u is ancestor-of-or t
    branch_nodes: np.ndarray  # [width, depth] int32 — flat ids, depth order


def make_tree_spec(width: int, depth: int) -> TreeSpec:
    """Precompute the flat layout + ancestor mask for an (N, K) tree."""
    width = max(1, int(width))
    depth = max(1, int(depth))
    T = 1 + width * depth
    depths = np.zeros((T,), np.int32)
    parent = np.zeros((T,), np.int32)
    branch_nodes = np.zeros((width, depth), np.int32)
    for n in range(width):
        for d in range(depth):
            t = 1 + n * depth + d
            branch_nodes[n, d] = t
            depths[t] = d + 1
            parent[t] = 0 if d == 0 else t - 1
    anc = np.zeros((T, T), bool)
    for t in range(T):
        u = t
        anc[t, t] = True
        while u != 0:
            u = parent[u]
            anc[t, u] = True
    return TreeSpec(
        width=width,
        depth=depth,
        size=T,
        depths=depths,
        parent=parent,
        anc_mask=anc,
        branch_nodes=branch_nodes,
    )


def build_tree_draft(
    history: jnp.ndarray,  # [B, S] int32 token history rows
    lengths: jnp.ndarray,  # [B] cache lengths; history[b, :lengths[b]] is valid
    tokens: jnp.ndarray,  # [B] the pending input token (sequence pos lengths[b])
    width: int,
    depth: int,
) -> jnp.ndarray:
    """Draft [B, width, depth]: the top-``width`` distinct continuations of the
    current tail bigram in each row's history, most recent first.

    Candidate ranking: every position where the tail bigram
    ``(history[n-2], tokens)`` occurred is a candidate start; candidates are
    DEDUPLICATED on their first continuation token (two hits proposing the
    same next token would waste tree width verifying it twice — the most
    recent occurrence survives, carrying the freshest continuation), then the
    ``width`` most recent survivors fill the branches.  The first branch is
    exactly the old single-candidate prompt-lookup draft, so (width=1) is a
    strict superset of the previous behavior.  One spare branch falls back to
    the unigram (last occurrence of ``tokens`` alone) when bigram hits don't
    fill the tree.  Unfilled branches draft from position ``n``
    (garbage/stale tokens) — their drafts are simply rejected by
    verification; correctness never depends on the draft.

    Cost: the dedup is an O(B*S^2) boolean compare — elementwise, fused, and
    at serving contexts still noise next to one decode matmul; the rest is
    O(B*S) like the original builder.
    """
    B, S = history.shape
    n = lengths + 1  # known sequence tokens incl. the pending input
    js = jnp.arange(S - 1)
    prev = jnp.take_along_axis(
        history, jnp.maximum(lengths - 1, 0)[:, None], axis=1
    )[:, 0]  # token before the pending input
    # bigram (prev, tokens) at (j, j+1), ending strictly before the tail bigram
    big = (history[:, :-1] == prev[:, None]) & (history[:, 1:] == tokens[:, None])
    big = big & ((js[None, :] + 1) < (n - 1)[:, None])
    # first continuation token of candidate j is history[j+2]
    first_tok = jnp.take_along_axis(
        history, jnp.clip(js + 2, 0, S - 1)[None, :].repeat(B, axis=0), axis=1
    )  # [B, S-1]
    # dedup on the first continuation token: candidate j is dominated when a
    # LATER candidate proposes the same next token (keep the most recent)
    same = first_tok[:, :, None] == first_tok[:, None, :]  # [B, j, j']
    later = js[None, :] > js[:, None]  # [j, j'] — j' more recent than j
    dominated = jnp.any(same & later[None] & big[:, None, :], axis=2)
    keep = big & ~dominated
    # width most recent distinct candidates, by position (desc)
    ranked = jnp.where(keep, js[None, :], -1)
    top_pos, _ = jax.lax.top_k(ranked, width)  # [B, width] positions, -1 = none
    n_big = jnp.sum(top_pos >= 0, axis=1)  # [B] filled bigram branches
    # unigram fallback: last occurrence of `tokens` strictly before pos n-1
    jsf = jnp.arange(S)
    uni = (history == tokens[:, None]) & (jsf[None, :] < (n - 1)[:, None])
    has1 = uni.any(axis=1)
    j1 = jnp.max(jnp.where(uni, jsf[None, :], -1), axis=1)
    bidx = jnp.arange(width)[None, :]  # [1, width]
    starts = jnp.where(
        top_pos >= 0,
        top_pos + 2,
        jnp.where(
            (bidx == n_big[:, None]) & has1[:, None],
            (j1 + 1)[:, None],
            n[:, None],  # unfilled: rejectable garbage from the tail
        ),
    )  # [B, width]
    idx = jnp.clip(
        starts[:, :, None] + jnp.arange(depth)[None, None, :], 0, S - 1
    )  # [B, width, depth]
    return jnp.take_along_axis(history[:, None, :], idx, axis=2)


def flatten_tree(tokens: jnp.ndarray, draft: jnp.ndarray) -> jnp.ndarray:
    """[B] input tokens + [B, N, K] branch drafts -> flat tree [B, T]."""
    B = tokens.shape[0]
    return jnp.concatenate([tokens[:, None], draft.reshape(B, -1)], axis=1)


def accept_tree(
    logits: jnp.ndarray,  # [B, T, V] f32 — verify logits over the flat tree
    tree: jnp.ndarray,  # [B, T] int32 flat tree tokens (col 0 = input token)
    spec: TreeSpec,
    rng: jax.Array,
    *,
    temperature: jnp.ndarray,  # [B]
    top_k: int,
    top_p: jnp.ndarray,  # [B]
):
    """Longest root-to-leaf acceptance + one bonus/corrected token per row.

    Returns ``(out [B, K+1], n_new [B] in [1, K+1], bonus [B], path_idx
    [B, K+1], rng)`` where ``out[b, :n_new[b]]`` are the new sequence tokens,
    ``bonus`` is the next tick's input token and ``path_idx`` are the flat
    tree indices whose K/V the caller must commit (root first, then the
    winning branch — garbage beyond the accepted run, exactly like ``out``).

    Greedy rows: branch node (n, d) is accepted iff the model's argmax at its
    PARENT equals its token AND every shallower node of the branch was
    accepted; the winning branch is the one with the longest accepted run
    (ties: lowest branch index, i.e. the most recent bigram hit), and the
    token after the run is the model's own argmax there — exactly what
    non-speculative greedy would have produced at every accepted position,
    so the equivalence contract of the linear verifier carries over
    unchanged.  Sampled rows accept nothing and sample position 0 with their
    own temperature/top-p, so one compiled program serves mixed batches.
    """
    from .sampling import sample_logits

    B = logits.shape[0]
    N, K = spec.width, spec.depth
    branch = jnp.asarray(spec.branch_nodes)  # [N, K]
    greedy_next = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, T]
    rng, sub = jax.random.split(rng)
    samp0 = sample_logits(
        logits[:, 0], sub, temperature=temperature, top_k=top_k, top_p=top_p
    )
    greedy_row = temperature <= 0.0
    # parent prediction for node (n, d): argmax at the parent node
    parent_idx = jnp.asarray(spec.parent)[branch]  # [N, K]
    pred = greedy_next[:, parent_idx.reshape(-1)].reshape(B, N, K)
    tok = jnp.take_along_axis(tree[:, None, :].repeat(N, 1), branch[None], axis=2)
    match = (tok == pred) & greedy_row[:, None, None]  # [B, N, K]
    acc = jnp.cumprod(match.astype(jnp.int32), axis=2).sum(axis=2)  # [B, N]
    best = jnp.argmax(acc, axis=1).astype(jnp.int32)  # [B] first max wins
    n_acc = jnp.take_along_axis(acc, best[:, None], axis=1)[:, 0]  # [B]
    win_nodes = branch[best]  # [B, K] flat ids of the winning branch
    # the node whose argmax is the bonus: root when nothing accepted, else
    # the deepest accepted node of the winning branch
    last_idx = jnp.where(
        n_acc > 0,
        jnp.take_along_axis(
            win_nodes, jnp.maximum(n_acc - 1, 0)[:, None], axis=1
        )[:, 0],
        0,
    )
    bonus_greedy = jnp.take_along_axis(greedy_next, last_idx[:, None], axis=1)[:, 0]
    # at temp<=0 sample_logits IS argmax, so samp0 == bonus_greedy when n_acc==0
    bonus = jnp.where(greedy_row, bonus_greedy, samp0)
    win_toks = jnp.take_along_axis(tree, win_nodes, axis=1)  # [B, K]
    js = jnp.arange(K + 1)[None, :]
    accepted = jnp.concatenate([win_toks, jnp.zeros((B, 1), tree.dtype)], axis=1)
    out = jnp.where(
        js < n_acc[:, None],
        accepted,
        jnp.where(js == n_acc[:, None], bonus[:, None], 0),
    ).astype(jnp.int32)
    # commit gather: root's K/V at output position 0, branch node d at 1 + d
    path_idx = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.int32), win_nodes.astype(jnp.int32)], axis=1
    )  # [B, K+1]
    n_new = n_acc + 1
    return out, n_new.astype(jnp.int32), bonus.astype(jnp.int32), path_idx, rng


# Backwards-compatible linear helpers -------------------------------------


def build_prompt_lookup_draft(
    history: jnp.ndarray,
    lengths: jnp.ndarray,
    tokens: jnp.ndarray,
    k: int,
) -> jnp.ndarray:
    """Single-candidate prompt-lookup draft [B, k] — the width-1 tree."""
    return build_tree_draft(history, lengths, tokens, 1, k)[:, 0]


def breakeven_accept_rate(cost_ratio: float, depth: int) -> float:
    """Per-position accept probability ``p`` at which a (·, depth) verify
    tick exactly pays for itself against a plain decode tick that costs
    ``1/cost_ratio`` as much: solves E[tokens/tick] = (1 - p^(K+1))/(1 - p)
    = cost_ratio by bisection.  cost_ratio <= 1 means speculation is free
    (breakeven 0); an unreachable ratio (> K+1 tokens/tick) returns 1.0.
    """
    K = max(1, int(depth))
    r = float(cost_ratio)
    if r <= 1.0:
        return 0.0
    if r >= K + 1:
        return 1.0

    def expected(p: float) -> float:
        if p >= 1.0:
            return K + 1.0
        return (1.0 - p ** (K + 1)) / (1.0 - p)

    lo, hi = 0.0, 1.0
    for _ in range(60):
        mid = (lo + hi) / 2.0
        if expected(mid) < r:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2.0


def default_rungs(width: int, depth: int) -> List[Tuple[int, int]]:
    """The controller's shrink ladder: full tree -> half width -> single
    branch -> half depth.  Deduplicated, widest first."""
    rungs: List[Tuple[int, int]] = []
    for w, k in (
        (width, depth),
        (max(1, width // 2), depth),
        (1, depth),
        (1, max(1, depth // 2)),
    ):
        if (w, k) not in rungs:
            rungs.append((w, k))
    return rungs


@dataclasses.dataclass
class SpecController:
    """Per-rung acceptance-EMA bandit over a ladder of precompiled tree
    shapes.

    Tree width only pays off by RAISING acceptance (more candidates per
    depth), so a single shared accept probability can never justify a wider
    tree over a narrower one — each rung keeps its OWN per-position
    accept-probability EMA ``p[rung]``, measured only from ticks that rung
    actually ran, initialised optimistically so every shape gets tried.
    Per tick the controller compares each rung's expected speedup
    ``E[tokens/tick] / cost_ratio(rung)`` with ``E = (1 - p^(K+1))/(1 - p)``
    (cost ratios measured by the engine: verify-tick seconds / plain-tick
    seconds).  Policy:

    - run the BEST rung by expected speedup, with a periodic exploration
      tick on the next-wider rung so a stale "width doesn't pay" estimate
      can be revised when the workload shifts;
    - when even the best rung's expected speedup is below ``margin``,
      disable speculation (plain ticks) — but re-probe with one speculative
      tick every ``probe_every`` ticks so a workload shift (e.g. the model
      starts quoting its context) can re-enable it;
    - composes with the scheduler's under-load disable, which is checked by
      the engine FIRST (an overloaded engine never speculates regardless of
      acceptance).

    Pure host-side python: deterministic, unit-testable without a device.
    """

    rungs: List[Tuple[int, int]]  # (width, depth) ladder, widest first
    alpha: float = 0.15  # acceptance EMA smoothing
    margin: float = 1.0  # minimum expected speedup to keep speculating
    probe_every: int = 64  # disabled-state re-probe cadence (ticks)
    explore_every: int = 32  # enabled-state wider-rung refresh cadence
    init_accept: float = 0.5  # optimistic prior: start speculating
    accept_ema: dict = dataclasses.field(default_factory=dict)  # rung -> p
    cost_ratio: dict = dataclasses.field(default_factory=dict)
    disabled: bool = False
    _ticks_since_probe: int = 0
    _ticks_since_explore: int = 0

    def __post_init__(self):
        if not self.rungs:
            raise ValueError("SpecController needs at least one rung")
        self.rungs = [tuple(r) for r in self.rungs]
        for rung in self.rungs:
            self.accept_ema.setdefault(rung, float(self.init_accept))
        self._rung_idx = 0

    # ---------------------------------------------------------------- inputs
    def note_cost(self, rung: Tuple[int, int], ratio: float) -> None:
        """Record a measured verify/plain tick-cost ratio for ``rung``."""
        self.cost_ratio[tuple(rung)] = max(1.0, float(ratio))

    def note_tick(
        self,
        accepted: int,
        depth: int,
        rows: int = 1,
        rung: Optional[Tuple[int, int]] = None,
    ) -> None:
        """Fold one speculative tick's greedy-row acceptance into ``rung``'s
        EMA: ``accepted`` drafts accepted out of ``depth`` offered, over
        ``rows`` greedy rows (rows==0 ticks carry no signal and are
        ignored).  ``rung=None`` resolves to the deepest ladder entry of
        that depth (back-compat for depth-only callers)."""
        if rows <= 0 or depth <= 0:
            return
        if rung is None:
            rung = next(
                (r for r in self.rungs if r[1] == depth), self.rungs[0]
            )
        rung = tuple(rung)
        rate = min(1.0, max(0.0, accepted / (rows * depth)))
        prev = self.accept_ema.get(rung, float(self.init_accept))
        self.accept_ema[rung] = (1 - self.alpha) * prev + self.alpha * rate

    # ---------------------------------------------------------------- policy
    def _cost(self, rung: Tuple[int, int]) -> float:
        got = self.cost_ratio.get(tuple(rung))
        if got is not None:
            return got
        # unmeasured default: each extra verified position costs a fraction
        # of a plain tick (attention grows, projections amortise) — a
        # deliberately conservative stand-in until the engine feeds a
        # measurement
        w, k = rung
        return 1.0 + 0.15 * (1 + w * k - 1)

    def expected_tokens(self, rung: Tuple[int, int]) -> float:
        p = min(self.accept_ema.get(tuple(rung), self.init_accept), 0.999999)
        k = rung[1]
        return (1.0 - p ** (k + 1)) / (1.0 - p)

    def expected_speedup(self, rung: Tuple[int, int]) -> float:
        return self.expected_tokens(rung) / self._cost(rung)

    def best_rung(self) -> Tuple[int, Tuple[int, int], float]:
        """(index, rung, expected speedup) of the best rung right now."""
        best_i, best_s = 0, -1.0
        for i, rung in enumerate(self.rungs):
            s = self.expected_speedup(rung)
            if s > best_s:
                best_i, best_s = i, s
        return best_i, self.rungs[best_i], best_s

    def rung(self) -> Optional[Tuple[int, int]]:
        """The tree shape to issue THIS tick, or None for a plain tick.

        Call exactly once per issued tick: while disabled it also advances
        the probe counter (returning a rung on probe ticks); while enabled
        it occasionally returns the next-WIDER rung than the current best to
        refresh that rung's acceptance estimate."""
        i, rung, speedup = self.best_rung()
        if not self.disabled:
            if speedup < self.margin:
                self.disabled = True
                self._ticks_since_probe = 0
                return None
            self._ticks_since_explore += 1
            if i > 0 and self._ticks_since_explore >= self.explore_every:
                # exploration: the wider neighbour's estimate may be stale —
                # one tick of evidence keeps the ladder climbable
                self._ticks_since_explore = 0
                self._rung_idx = i - 1
                return self.rungs[i - 1]
            self._rung_idx = i
            return rung
        # disabled: mostly plain ticks, with a periodic speculative probe so
        # acceptance evidence keeps flowing (otherwise disable is forever)
        self._ticks_since_probe += 1
        if self._ticks_since_probe >= self.probe_every:
            self._ticks_since_probe = 0
            self._rung_idx = i
            return rung
        if speedup >= self.margin:
            self.disabled = False
            self._rung_idx = i
            return rung
        return None

    def current(self) -> Tuple[int, int]:
        """The rung most recently issued (for stats/gauges)."""
        return self.rungs[self._rung_idx]

    def stats(self) -> dict:
        w, k = self.current()
        i, rung, speedup = self.best_rung()
        return {
            "spec_accept_ema": round(
                self.accept_ema.get((w, k), self.init_accept), 4
            ),
            "spec_tree_width": w,
            "spec_tree_depth": k,
            "spec_auto_disabled": self.disabled,
            "spec_expected_speedup": round(speedup, 3),
            # per-arm acceptance: each tree shape's own measured EMA (rungs
            # that never ran still show the optimistic prior)
            "spec_rung_accept_emas": {
                f"{rw}x{rk}": round(p, 4)
                for (rw, rk), p in sorted(self.accept_ema.items())
            },
        }
