"""Shape-static token sampling (temperature / top-k / top-p) for the decode loop.

The reference samples via torch ``generate(do_sample=True, top_p=0.95, top_k=50)``
(reference: assistant/ai/providers/transformers.py:61-68).  Here sampling lives inside
the jit'd decode step: all ops are static-shape (sort + cumsum masking), so the whole
prefill→decode loop stays on-device with no host round-trip per token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .attention import NEG_INF

# Use hierarchical top-k above this vocab size; below it plain lax.top_k wins
# (the two-stage version's gather overhead isn't worth it on small vocabs).
_HIER_TOPK_MIN_VOCAB = 16_384
_GROUP = 128  # lane width — group reductions vectorize cleanly


def top_k_hierarchical(x: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact top-k over a large last axis in two small stages.

    ``lax.top_k`` over a 128k vocab costs ~7 ms/step on a v5e-class chip —
    measured at ~70% of the whole 1B decode step (the sort dwarfs the model).
    Instead: reduce each 128-lane group to its max (one cheap pass), take the
    top-k GROUPS by max, gather only those groups' lanes (k*128 candidates)
    and top-k within them.

    Exactness: if an element x is in the global top-k, at most k-1 groups can
    have max > x (each would contribute an element > x, outranking it), so
    x's group is always among the top-k groups by max.  Ties at the boundary
    may pick different (equal-valued) ids than lax.top_k — same top-k SET of
    values either way.

    Returns (values [B, k] desc, indices [B, k] int32) like ``lax.top_k``.
    """
    B, V = x.shape
    G = -(-V // _GROUP)  # ceil
    pad = G * _GROUP - V
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad)), constant_values=NEG_INF)
    xg = x.reshape(B, G, _GROUP)
    gmax = xg.max(axis=-1)  # [B, G]
    kg = min(k, G)
    _, gidx = jax.lax.top_k(gmax, kg)  # [B, kg] group ids
    cand = jnp.take_along_axis(xg, gidx[:, :, None], axis=1).reshape(B, kg * _GROUP)
    vals, cidx = jax.lax.top_k(cand, k)  # [B, k] within candidates
    idx = jnp.take_along_axis(gidx, cidx // _GROUP, axis=1) * _GROUP + cidx % _GROUP
    # Pad lanes hold NEG_INF (finite): with fewer than k candidates above it
    # (e.g. a degenerate FSM state masking everything at an unaligned vocab) a
    # pad lane can win a slot and carry an index >= V — and a uniform draw over
    # all-NEG_INF rows could then emit an out-of-vocab id.  lax.top_k never
    # returns out-of-range ids; match that contract by clamping (the clamped
    # slot's value is still NEG_INF, so it can't outrank any real candidate).
    idx = jnp.minimum(idx, V - 1)
    return vals, idx.astype(jnp.int32)


def top_k_auto(x: jnp.ndarray, k: int) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Size-dispatching exact top-k: hierarchical past _HIER_TOPK_MIN_VOCAB
    (where the flat sort's cost dominates), plain lax.top_k below it."""
    if x.shape[-1] >= _HIER_TOPK_MIN_VOCAB:
        return top_k_hierarchical(x, k)
    vals, idx = jax.lax.top_k(x, k)
    return vals, idx.astype(jnp.int32)


_top_k = top_k_auto  # internal alias used by sample_logits


def sample_logits(
    logits: jnp.ndarray,  # [batch, vocab] float
    rng: jax.Array,
    *,
    temperature: jnp.ndarray | float = 1.0,  # [batch] or scalar; <=0 means greedy
    top_k: int = 50,
    top_p: jnp.ndarray | float = 0.95,  # [batch] or scalar
) -> jnp.ndarray:
    """Returns sampled token ids [batch] (int32).

    Greedy is expressed per-row via temperature<=0 so one compiled fn serves mixed
    batches (continuous batching requirement: different requests, one XLA program).
    """
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    temperature = jnp.asarray(temperature, dtype=jnp.float32)
    temperature = jnp.broadcast_to(temperature, (logits.shape[0],))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, dtype=jnp.float32), (logits.shape[0],))

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    if top_k and 0 < top_k < V:
        # Everything past top_k is filtered anyway, so top-p and the draw both
        # live in the [B, top_k] subspace (hierarchical top-k at large vocab —
        # a full-vocab lax.top_k was ~70% of the whole 1B decode step); the
        # cumsum runs over 50 values and categorical draws over 50.  Greedy
        # rows reuse the candidates' head (sorted desc) — no argmax pass.
        vals, idx = _top_k(scaled, top_k)  # [B, k] desc + their ids
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p[:, None]  # first token always kept
        vals = jnp.where(keep, vals, NEG_INF)
        choice = jax.random.categorical(rng, vals, axis=-1)  # [B] in [0, k)
        sampled = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, idx[:, 0])

    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    # no top-k bound: top-p needs the full distribution sorted
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    keep_sorted = (cum - sorted_probs) < top_p[:, None]  # first token always kept
    # threshold = smallest kept logit
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    scaled = jnp.where(scaled < threshold, NEG_INF, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy_ids)
