"""Shape-static token sampling (temperature / top-k / top-p) for the decode loop.

The reference samples via torch ``generate(do_sample=True, top_p=0.95, top_k=50)``
(reference: assistant/ai/providers/transformers.py:61-68).  Here sampling lives inside
the jit'd decode step: all ops are static-shape (sort + cumsum masking), so the whole
prefill→decode loop stays on-device with no host round-trip per token.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .attention import NEG_INF


def sample_logits(
    logits: jnp.ndarray,  # [batch, vocab] float
    rng: jax.Array,
    *,
    temperature: jnp.ndarray | float = 1.0,  # [batch] or scalar; <=0 means greedy
    top_k: int = 50,
    top_p: jnp.ndarray | float = 0.95,  # [batch] or scalar
) -> jnp.ndarray:
    """Returns sampled token ids [batch] (int32).

    Greedy is expressed per-row via temperature<=0 so one compiled fn serves mixed
    batches (continuous batching requirement: different requests, one XLA program).
    """
    logits = logits.astype(jnp.float32)
    V = logits.shape[-1]
    temperature = jnp.asarray(temperature, dtype=jnp.float32)
    temperature = jnp.broadcast_to(temperature, (logits.shape[0],))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, dtype=jnp.float32), (logits.shape[0],))

    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    safe_t = jnp.where(temperature > 0, temperature, 1.0)
    scaled = logits / safe_t[:, None]

    if top_k and 0 < top_k < V:
        # Everything past top_k is filtered anyway, so top-p and the draw both
        # live in the [B, top_k] subspace: lax.top_k already returns candidates
        # sorted descending, the cumsum runs over 50 values instead of a
        # full-vocab sort, and categorical draws over 50 — at 128k vocab this
        # is the difference between ~6 ms and ~0.5 ms per decode step.
        vals, idx = jax.lax.top_k(scaled, top_k)  # [B, k] desc + their ids
        probs = jax.nn.softmax(vals, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = (cum - probs) < top_p[:, None]  # first token always kept
        vals = jnp.where(keep, vals, NEG_INF)
        choice = jax.random.categorical(rng, vals, axis=-1)  # [B] in [0, k)
        sampled = jnp.take_along_axis(idx, choice[:, None], axis=1)[:, 0].astype(jnp.int32)
        return jnp.where(temperature > 0, sampled, greedy_ids)

    # no top-k bound: top-p needs the full distribution sorted
    sorted_logits = jnp.sort(scaled, axis=-1)[:, ::-1]
    sorted_probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(sorted_probs, axis=-1)
    keep_sorted = (cum - sorted_probs) < top_p[:, None]  # first token always kept
    # threshold = smallest kept logit
    threshold = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    scaled = jnp.where(scaled < threshold, NEG_INF, scaled)

    sampled = jax.random.categorical(rng, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy_ids)
