"""Normalisation ops.

Computed in float32 regardless of activation dtype (bf16-safe), shaped so XLA fuses
them into the neighbouring matmuls — no pallas needed; fusion is the win here.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """Llama-style RMSNorm: x / rms(x) * w, stats in f32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    y = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-12
) -> jnp.ndarray:
    """BERT-style LayerNorm (the encoder family uses post-LN), stats in f32."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(dtype)
