"""Rotary position embeddings (Llama-3 family, incl. the 500k theta variant).

Frequencies are precomputed once on host and live in HBM; application is two fused
elementwise multiplies — XLA folds them into the QK projection epilogue.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    max_len: int,
    theta: float = 500_000.0,
    scaling: "tuple | None" = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Return (cos, sin) tables of shape [max_len, head_dim//2] in float32.

    ``scaling`` applies the Llama-3.1 frequency remap as a 4-tuple
    ``(factor, low_freq_factor, high_freq_factor, original_max_len)``:
    long-wavelength (low-frequency) components stretch by ``factor``,
    short-wavelength ones stay, and the band between interpolates smoothly —
    a one-time host-side table edit, free at run time."""
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if scaling is not None:
        factor, low_f, high_f, orig = scaling
        wavelen = 2.0 * np.pi / inv_freq
        low_wavelen = orig / low_f
        high_wavelen = orig / high_f
        smooth = np.clip(
            (orig / wavelen - low_f) / (high_f - low_f), 0.0, 1.0
        )
        interp = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = np.where(
            wavelen > low_wavelen,
            inv_freq / factor,
            np.where(wavelen < high_wavelen, inv_freq, interp),
        )
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv_freq)
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rope(
    x: jnp.ndarray,  # [..., seq, heads, head_dim]
    cos: jnp.ndarray,  # [seq, head_dim//2] (already gathered at positions)
    sin: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate pairs (x[..i], x[..i+D/2]) — the half-split ("rotate_half") convention
    HF Llama safetensors use.  Checkpoints in the interleaved GPT-J/NeoX layout must
    be permuted at load time.  ``cos``/``sin`` broadcast over heads."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    # cos/sin: [seq, hd/2] -> [seq, 1, hd/2] to broadcast over the heads axis.
    c = cos[..., :, None, :]
    s = sin[..., :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
