"""Rotary position embeddings (Llama-3 family, incl. the 500k theta variant).

Frequencies are precomputed once on host and live in HBM; application is two fused
elementwise multiplies — XLA folds them into the QK projection epilogue.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int, max_len: int, theta: float = 500_000.0
) -> tuple[np.ndarray, np.ndarray]:
    """Return (cos, sin) tables of shape [max_len, head_dim//2] in float32."""
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv_freq)
    return np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32)


def apply_rope(
    x: jnp.ndarray,  # [..., seq, heads, head_dim]
    cos: jnp.ndarray,  # [seq, head_dim//2] (already gathered at positions)
    sin: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate pairs (x[..i], x[..i+D/2]) — the half-split ("rotate_half") convention
    HF Llama safetensors use.  Checkpoints in the interleaved GPT-J/NeoX layout must
    be permuted at load time.  ``cos``/``sin`` broadcast over heads."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    # cos/sin: [seq, hd/2] -> [seq, 1, hd/2] to broadcast over the heads axis.
    c = cos[..., :, None, :]
    s = sin[..., :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
