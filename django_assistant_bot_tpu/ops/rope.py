"""Rotary position embeddings (Llama-3 family, incl. the 500k theta variant).

Frequencies are precomputed once on host and live in HBM; application is two fused
elementwise multiplies — XLA folds them into the QK projection epilogue.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def rope_frequencies(
    head_dim: int,
    max_len: int,
    theta: float = 500_000.0,
    scaling: "tuple | None" = None,
    deployed_len: "int | None" = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Return (cos, sin) tables of shape [max_len, head_dim//2] in float32.

    ``scaling`` selects a long-context frequency remap — every variant is a
    one-time host-side table edit, free at run time (HF recomputes these per
    forward; reference semantics: transformers modeling_rope_utils):

    - 4-tuple ``(factor, low_freq_factor, high_freq_factor, original_max_len)``
      — Llama-3.1: long wavelengths stretch by ``factor``, short ones stay,
      the band between interpolates smoothly.
    - ``("linear", factor)`` — position interpolation: every frequency /factor.
    - ``("longrope", short_factors, long_factors, original_max,
      attention_factor)`` — Phi-3 128k: per-frequency rescale lists; the long
      list engages when the deployed context exceeds the pretrained one, and
      cos/sin scale by ``attention_factor``.
    - ``("yarn", factor, beta_fast, beta_slow, original_max,
      attention_factor, truncate)`` — NTK-by-parts: interpolate only below the
      correction band, extrapolate above, linear ramp between; cos/sin scale
      by the mscale ``attention_factor``.
    """
    dim = head_dim
    inv_freq = 1.0 / (theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    attention_factor = 1.0
    if scaling is not None and scaling[0] == "linear":
        inv_freq = inv_freq / float(scaling[1])
    elif scaling is not None and scaling[0] == "longrope":
        _, short_f, long_f, orig, attention_factor = scaling
        # the short/long choice keys on the DEPLOYED context (``deployed_len``,
        # normally cfg.max_seq_len), NOT this table's length: prefill builds
        # bucket-sized tables while decode builds cache-sized ones, and the
        # factor list must be IDENTICAL across them or cached K vectors and
        # decode queries rotate differently.  HF flips per running sequence
        # (transformers _longrope_frequency_update); a static-shape serving
        # stack commits once per deployment, agreeing with HF whenever the
        # deployment targets the long regime (see tests).
        use_long = (deployed_len or max_len) > orig
        if use_long:
            import warnings

            # a Phi-3-128k-style deployment with max_seq_len > original_max
            # applies the LONG factors to every sequence — prompts shorter
            # than `orig` get slightly different rotations than HF, which
            # switches factor lists per running sequence.  Deploy with
            # max_seq_len <= original_max when exact short-prompt HF parity
            # matters (VERDICT r4 missing #2).
            warnings.warn(
                f"longrope: deployed context {deployed_len or max_len} > "
                f"pretrained {orig}; committing to the LONG factor list for "
                "ALL sequences — short prompts diverge from HF, which flips "
                "short/long per sequence. Deploy with max_seq_len <= "
                f"{orig} if exact short-prompt HF parity matters.",
                stacklevel=2,
            )
        ext = np.asarray(long_f if use_long else short_f, np.float64)
        inv_freq = 1.0 / (ext * theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim))
    elif scaling is not None and scaling[0] == "yarn":
        _, factor, beta_fast, beta_slow, orig, attention_factor, truncate = scaling
        pos_freqs = theta ** (np.arange(0, dim, 2, dtype=np.float64) / dim)
        inv_extra = 1.0 / pos_freqs
        inv_inter = 1.0 / (factor * pos_freqs)

        def corr_dim(num_rot):
            return (dim * np.log(orig / (num_rot * 2 * np.pi))) / (2 * np.log(theta))

        low, high = corr_dim(beta_fast), corr_dim(beta_slow)
        if truncate:
            low, high = np.floor(low), np.ceil(high)
        low, high = max(low, 0.0), min(high, dim - 1.0)
        if low == high:
            high += 0.001  # prevent singularity
        ramp = np.clip((np.arange(dim // 2, dtype=np.float64) - low) / (high - low), 0.0, 1.0)
        extra_factor = 1.0 - ramp
        inv_freq = inv_inter * (1.0 - extra_factor) + inv_extra * extra_factor
    elif scaling is not None:
        factor, low_f, high_f, orig = scaling
        wavelen = 2.0 * np.pi / inv_freq
        low_wavelen = orig / low_f
        high_wavelen = orig / high_f
        smooth = np.clip(
            (orig / wavelen - low_f) / (high_f - low_f), 0.0, 1.0
        )
        interp = (1.0 - smooth) * inv_freq / factor + smooth * inv_freq
        inv_freq = np.where(
            wavelen > low_wavelen,
            inv_freq / factor,
            np.where(wavelen < high_wavelen, inv_freq, interp),
        )
    t = np.arange(max_len, dtype=np.float64)
    freqs = np.outer(t, inv_freq)
    cos = (np.cos(freqs) * attention_factor).astype(np.float32)
    sin = (np.sin(freqs) * attention_factor).astype(np.float32)
    return cos, sin


def apply_rope(
    x: jnp.ndarray,  # [..., seq, heads, head_dim]
    cos: jnp.ndarray,  # [seq, head_dim//2] (already gathered at positions)
    sin: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate pairs (x[..i], x[..i+D/2]) — the half-split ("rotate_half") convention
    HF Llama safetensors use.  Checkpoints in the interleaved GPT-J/NeoX layout must
    be permuted at load time.  ``cos``/``sin`` broadcast over heads."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    # cos/sin: [seq, hd/2] -> [seq, 1, hd/2] to broadcast over the heads axis.
    c = cos[..., :, None, :]
    s = sin[..., :, None, :]
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(dtype)
