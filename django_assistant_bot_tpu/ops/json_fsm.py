"""Grammar-constrained JSON decoding: char-level DFA -> token-level device tables.

SURVEY.md §7 hard part (d).  The reference gets JSON out of its models by asking
nicely and retrying up to 5 times with an LLM-side repair loop (reference:
assistant/ai/providers/ollama.py:49-107).  Here the decoder *cannot* emit invalid
JSON: a deterministic automaton over the JSON grammar rides inside the jit'd decode
tick as two HBM-resident tables,

- ``next_state[state, token] -> state`` (dead state = invalid), and
- ``allowed[state, token]`` (= next_state != dead, with EOS handled specially),

so constrained sampling is one gather + one mask per tick — no host round trip,
fully compatible with the engine's lookahead pipeline (the FSM state chains
device-to-device exactly like the sampled-token array).

Construction is two-stage, Outlines-style but from scratch:

1. a char-level DFA over bytes for JSON values with a *bounded container stack*
   (object/array nesting encoded in the state, depth <= ``max_depth``), built by
   BFS over reachable (mode, stack) pairs;
2. closure over the tokenizer: a token is allowed in state ``s`` iff consuming its
   bytes from ``s`` never hits the dead state; computed vectorised over all
   (state, token) pairs at once.

Generation under the mask always terminates at a *complete* top-level object or
array: accepting states allow only EOS.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

WS = frozenset(b" \t\n\r")
DIGITS = frozenset(b"0123456789")
HEX = frozenset(b"0123456789abcdefABCDEF")
ESCAPABLE = frozenset(b'"\\/bfnrt')

# number phases that form a complete number (a terminator char may follow)
_NUM_COMPLETE = {"int", "int0", "frac", "exp"}


def _after_value(stack: tuple):
    if not stack:
        return ("done", ())
    return (("obj_comma", stack) if stack[-1] == "o" else ("arr_comma", stack))


def _start_value(stack: tuple, c: int, max_depth: int):
    """Dispatch the first char of a JSON value (stack already reflects context)."""
    if c == ord("{"):
        if len(stack) >= max_depth:
            return None
        return ("obj_open", stack + ("o",))
    if c == ord("["):
        if len(stack) >= max_depth:
            return None
        return ("arr_open", stack + ("a",))
    if c == ord('"'):
        return (("str", "val"), stack)
    if c == ord("-"):
        return (("num", "minus"), stack)
    if c == ord("0"):
        return (("num", "int0"), stack)
    if c in DIGITS:
        return (("num", "int"), stack)
    if c == ord("t"):
        return (("lit", "rue"), stack)
    if c == ord("f"):
        return (("lit", "alse"), stack)
    if c == ord("n"):
        return (("lit", "ull"), stack)
    return None


def _char_step(state, c: int, max_depth: int):
    """One byte through the automaton.  state = (mode, stack); None = dead."""
    mode, stack = state

    if mode == "done":
        return None  # accepting: only EOS may follow

    if mode == "top":
        if c in WS:
            return state
        if c in (ord("{"), ord("[")):  # top level restricted to object/array
            return _start_value(stack, c, max_depth)
        return None

    if mode == "value":
        if c in WS:
            return state
        return _start_value(stack, c, max_depth)

    if mode == "obj_open":  # just after '{'
        if c in WS:
            return state
        if c == ord('"'):
            return (("str", "key"), stack)
        if c == ord("}"):
            return _after_value(stack[:-1])
        return None

    if mode == "obj_key":  # after ',' in an object
        if c in WS:
            return state
        if c == ord('"'):
            return (("str", "key"), stack)
        return None

    if mode == "colon":
        if c in WS:
            return state
        if c == ord(":"):
            return ("value", stack)
        return None

    if mode == "obj_comma":  # after a value inside an object
        if c in WS:
            return state
        if c == ord(","):
            return ("obj_key", stack)
        if c == ord("}"):
            return _after_value(stack[:-1])
        return None

    if mode == "arr_open":  # just after '['
        if c in WS:
            return state
        if c == ord("]"):
            return _after_value(stack[:-1])
        return _start_value(stack, c, max_depth)

    if mode == "arr_comma":  # after a value inside an array
        if c in WS:
            return state
        if c == ord(","):
            return ("value", stack)
        if c == ord("]"):
            return _after_value(stack[:-1])
        return None

    if isinstance(mode, tuple) and mode[0] == "str":
        tag = mode[1]
        if c == ord('"'):
            return (("colon", stack) if tag == "key" else _after_value(stack))
        if c == ord("\\"):
            return (("esc", tag), stack)
        if c >= 0x20:  # any non-control byte incl. UTF-8 continuation bytes
            return state
        return None

    if isinstance(mode, tuple) and mode[0] == "esc":
        tag = mode[1]
        if c in ESCAPABLE:
            return (("str", tag), stack)
        if c == ord("u"):
            return (("hex", tag, 4), stack)
        return None

    if isinstance(mode, tuple) and mode[0] == "hex":
        tag, left = mode[1], mode[2]
        if c in HEX:
            return (("str", tag), stack) if left == 1 else (("hex", tag, left - 1), stack)
        return None

    if isinstance(mode, tuple) and mode[0] == "lit":
        rest = mode[1]
        if c == rest[0] if isinstance(rest[0], int) else c == ord(rest[0]):
            rest2 = rest[1:]
            return _after_value(stack) if not rest2 else (("lit", rest2), stack)
        return None

    if isinstance(mode, tuple) and mode[0] == "num":
        phase = mode[1]
        if phase == "minus":
            if c == ord("0"):
                return (("num", "int0"), stack)
            if c in DIGITS:
                return (("num", "int"), stack)
            return None
        if phase == "int0":  # a single leading 0
            if c == ord("."):
                return (("num", "frac0"), stack)
            if c in (ord("e"), ord("E")):
                return (("num", "exp0"), stack)
            # 0 followed by digit is invalid JSON; terminator handled below
        elif phase == "int":
            if c in DIGITS:
                return state
            if c == ord("."):
                return (("num", "frac0"), stack)
            if c in (ord("e"), ord("E")):
                return (("num", "exp0"), stack)
        elif phase == "frac0":
            return (("num", "frac"), stack) if c in DIGITS else None
        elif phase == "frac":
            if c in DIGITS:
                return state
            if c in (ord("e"), ord("E")):
                return (("num", "exp0"), stack)
        elif phase == "exp0":
            if c in (ord("+"), ord("-")):
                return (("num", "exp0s"), stack)
            return (("num", "exp"), stack) if c in DIGITS else None
        elif phase == "exp0s":
            return (("num", "exp"), stack) if c in DIGITS else None
        elif phase == "exp":
            if c in DIGITS:
                return state
        # complete number + terminator: resolve the value, re-apply the char
        if phase in _NUM_COMPLETE:
            return _char_step(_after_value(stack), c, max_depth)
        return None

    raise AssertionError(f"unknown mode {mode!r}")


@dataclasses.dataclass
class CharDFA:
    table: np.ndarray  # [S, 257] int32; column 256 is the identity/pad column
    initial: int
    dead: int
    accepting: np.ndarray  # [S] bool


def build_char_dfa(max_depth: int = 4) -> CharDFA:
    """Enumerate reachable (mode, stack) states by BFS and tabulate transitions."""
    initial = ("top", ())
    index: Dict = {initial: 0}
    order = [initial]
    rows: List[List[Optional[Tuple]]] = []
    i = 0
    while i < len(order):
        state = order[i]
        row: List[Optional[Tuple]] = []
        for c in range(256):
            nxt = _char_step(state, c, max_depth)
            if nxt is not None and nxt not in index:
                index[nxt] = len(order)
                order.append(nxt)
            row.append(nxt)
        rows.append(row)
        i += 1

    S = len(order) + 1  # + dead state
    dead = S - 1
    table = np.full((S, 257), dead, np.int32)
    for si, row in enumerate(rows):
        for c, nxt in enumerate(row):
            if nxt is not None:
                table[si, c] = index[nxt]
    table[:, 256] = np.arange(S)  # pad column: identity (used by the token closure)
    table[dead, :] = dead
    accepting = np.zeros((S,), bool)
    for st, si in index.items():
        if st[0] == "done":
            accepting[si] = True
    return CharDFA(table=table, initial=0, dead=dead, accepting=accepting)


@dataclasses.dataclass
class TokenFSM:
    next_state: np.ndarray  # [S, V] int32
    allowed: np.ndarray  # [S, V] bool — in accepting states only EOS is allowed
    initial: int
    dead: int
    accepting: np.ndarray  # [S] bool


def token_bytes_for(tokenizer) -> List[bytes]:
    """Byte string each token id appends to the output stream.

    Tokenizers that know their exact byte tables expose ``token_bytes()``
    (ByteTokenizer does).  For HF/SentencePiece tokenizers, a bare
    ``decode([i])`` is unsound — it strips the leading-space marker (``▁true``
    renders as ``"true"``, losing the space) — so each token is rendered *after*
    an anchor token and the anchor's prefix is stripped, preserving interior
    spacing (the Outlines-style construction)."""
    if hasattr(tokenizer, "token_bytes"):
        return tokenizer.token_bytes()
    V = getattr(tokenizer, "vocab_size", None)
    if V is None:
        raise ValueError("tokenizer must expose vocab_size for constrained decoding")
    special = {tokenizer.eos_id, tokenizer.pad_id, getattr(tokenizer, "bos_id", -1)}
    anchor_ids = [i for i in tokenizer.encode(":") if i not in special]
    anchor = anchor_ids[-1] if anchor_ids else None
    prefix = tokenizer.decode([anchor]) if anchor is not None else ""
    out = []
    for i in range(V):
        if i in special:
            out.append(b"")
            continue
        if anchor is not None:
            s = tokenizer.decode([anchor, i])
            text = s[len(prefix):] if s.startswith(prefix) else tokenizer.decode([i])
        else:
            text = tokenizer.decode([i])
        out.append(text.encode("utf-8"))
    return out


def build_token_fsm(
    dfa: CharDFA, token_bytes: Sequence[bytes], eos_id: int
) -> TokenFSM:
    """Close the char DFA over whole tokens, vectorised over (state, token)."""
    S = dfa.table.shape[0]
    V = len(token_bytes)
    max_len = max((len(b) for b in token_bytes), default=1) or 1
    chars = np.full((V, max_len), 256, np.int32)  # 256 = identity pad column
    for i, b in enumerate(token_bytes):
        if b:
            chars[i, : len(b)] = np.frombuffer(b, np.uint8)

    cur = np.broadcast_to(np.arange(S, dtype=np.int32)[:, None], (S, V)).copy()
    for pos in range(max_len):
        cur = dfa.table[cur, chars[None, :, pos]]

    next_state = cur
    allowed = next_state != dfa.dead
    # empty tokens (specials, zero-byte artifacts) would self-loop forever
    empty = np.asarray([len(b) == 0 for b in token_bytes])
    allowed[:, empty] = False
    next_state = np.where(allowed, next_state, dfa.dead)
    # EOS: allowed exactly in accepting states (and nothing else is)
    allowed[dfa.accepting, :] = False
    if 0 <= eos_id < V:
        allowed[dfa.accepting, eos_id] = True
        next_state[dfa.accepting, eos_id] = np.flatnonzero(dfa.accepting)[0]
    return TokenFSM(
        next_state=next_state,
        allowed=allowed,
        initial=dfa.initial,
        dead=dfa.dead,
        accepting=dfa.accepting,
    )


def fsm_for_tokenizer(tokenizer, *, max_depth: int = 4) -> TokenFSM:
    return build_token_fsm(
        build_char_dfa(max_depth), token_bytes_for(tokenizer), tokenizer.eos_id
    )
