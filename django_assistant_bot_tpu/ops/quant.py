"""Weight-only int8 / grouped int4 quantization for the decode path.

Decode is HBM-bandwidth-bound: every generated token re-reads all layer
weights, so halving the bytes (bf16 -> int8 + per-channel f32 scales) nearly
doubles the decode roofline on real hardware and halves host->HBM transfer at
load — and int4 halves it again (0.5 bytes/weight packed).  The reference has
no quantization (torch fp16 generate,
assistant/ai/providers/transformers.py:22-29); this is a TPU-first extra.

Scheme (int8): symmetric per-output-channel.  Every projection weight in this
codebase is laid out ``[..., in, out]`` with the contraction on axis -2
(layer-stacked: wq/wk/wv [L,E,O], wo [L,O,E], MLP [L,(X,)E,F] / [L,(X,)F,E]),
so one rule quantizes them all: ``scale = max|w| over axis -2 / 127``.

``QTensor`` is a NamedTuple (automatically a pytree): the scale keeps the
weight's rank with the contracted dim = 1, so it scans along the layer axis
with the weights AND accepts the same PartitionSpec — ``shard_pytree``'s
sharding tree applies to a QTensor node as a pytree prefix, no rule changes.

Scheme (int4, docs/QUANT.md): symmetric per-GROUP — 4 bits cannot carry a
whole channel's dynamic range, so the contraction axis is cut into groups of
``group_size`` and each (group, output-channel) pair gets its own f32 scale.
Values pack two-per-byte along the contraction axis (``QTensor4.q`` is uint8
``[..., in/2, out]``, low nibble = even index); the scale is
``[..., in/group, out]`` — same rank as the weight, so the same pytree-prefix
sharding trick applies (group count replaces the contracted dim).

Dequantization sits inside the einsum callsites (:func:`deq` /
:func:`qeinsum`); XLA fuses the convert-multiply into the dot, so the bf16
weights are never materialized in HBM — the packed integers are what gets
read.  For int4 the per-group scales do NOT commute past the whole dot, but
they commute past each group's partial dot: ``qeinsum`` contracts group-wise
and applies the scale to the [..., G, out] partials before the final
group-sum, keeping the weight operand an integer load end to end.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax.numpy as jnp
import ml_dtypes
import numpy as np

QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")

# default int4 group width along the contraction axis: small enough that one
# outlier cannot wash out a whole channel's resolution, large enough that the
# f32 scales stay < 7% of the packed weight bytes (64 groups -> 4/64 bytes of
# scale per 0.5-byte weight)
INT4_GROUP_SIZE = 64


class QTensor(NamedTuple):
    q: jnp.ndarray      # int8, original shape
    scale: jnp.ndarray  # f32, same rank, contracted (-2) dim = 1


class QTensor4(NamedTuple):
    """Group-quantized int4 weight: two values per byte along axis -2.

    ``q``: uint8 ``[..., in/2, out]`` — the low nibble holds the even
    contraction index, the high nibble the odd one, each a two's-complement
    4-bit value in [-8, 7].  ``scale``: f32 ``[..., in/group_size, out]`` —
    one scale per (contraction group, output channel).  The group size is
    derived from the shapes (``2 * q.shape[-2] // scale.shape[-2]``), so the
    tuple stays a pure-array pytree (scans/shards like QTensor)."""

    q: jnp.ndarray
    scale: jnp.ndarray

    @property
    def group_size(self) -> int:
        return 2 * self.q.shape[-2] // self.scale.shape[-2]


def quantize_tensor(w) -> QTensor:
    """Symmetric per-output-channel int8 over contraction axis -2.

    Runs on HOST numpy: an on-device f32 upcast of a layer-stacked weight
    would double the bf16 footprint on one chip at exactly the moment
    quantization is supposed to shrink it.  shard_pytree transfers the int8
    result afterwards."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.rint(wf / scale), -127, 127).astype(np.int8)
    return QTensor(q=q, scale=scale)


def _int4_group(dim: int, group_size: int) -> int:
    """Concrete group width for a contraction dim: the largest even divisor
    of ``dim`` that is <= ``group_size`` (scales must tile the axis exactly,
    and an odd group would split a packed byte across two groups)."""
    g = max(2, min(int(group_size), dim))
    while dim % g or g % 2:
        g -= 1
        if g < 2:
            raise ValueError(
                f"int4 needs an even contraction dim with an even divisor "
                f"group size; got dim={dim}, group_size={group_size}"
            )
    return g


def pack_int4(vals: np.ndarray) -> np.ndarray:
    """Pack int values in [-8, 7] two-per-byte along axis -2 -> uint8 with
    half the axis length.  Low nibble = even index, high nibble = odd."""
    if vals.shape[-2] % 2:
        raise ValueError(f"contraction dim {vals.shape[-2]} must be even to pack")
    u = (np.asarray(vals).astype(np.int16) & 0xF).astype(np.uint8)
    return (u[..., 0::2, :] | (u[..., 1::2, :] << 4)).astype(np.uint8)


def unpack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """uint8 ``[..., P, O]`` -> int8 ``[..., 2P, O]`` (sign-extended nibbles).

    Pure elementwise bit ops — XLA fuses them into the consuming dot's
    operand load, so HBM traffic stays at the packed 0.5 bytes/weight."""
    lo = jnp.bitwise_and(q, jnp.uint8(0xF)).astype(jnp.int8)
    hi = jnp.right_shift(q, jnp.uint8(4)).astype(jnp.int8)
    lo = (lo ^ jnp.int8(8)) - jnp.int8(8)  # two's-complement nibble decode
    hi = (hi ^ jnp.int8(8)) - jnp.int8(8)
    both = jnp.stack([lo, hi], axis=-2)  # [..., P, 2, O]
    return both.reshape(q.shape[:-2] + (2 * q.shape[-2], q.shape[-1]))


def quantize_tensor_int4(w, group_size: int = INT4_GROUP_SIZE) -> QTensor4:
    """Symmetric per-(group, output-channel) int4 over contraction axis -2.

    HOST numpy like :func:`quantize_tensor` (same no-device-staging
    rationale); ``shard_pytree`` transfers the packed result afterwards."""
    wf = np.asarray(w, np.float32)
    dim, out_dim = wf.shape[-2], wf.shape[-1]
    g = _int4_group(dim, group_size)
    grp = wf.reshape(wf.shape[:-2] + (dim // g, g, out_dim))
    amax = np.max(np.abs(grp), axis=-2, keepdims=True)  # [..., G, 1, O]
    scale = np.maximum(amax / 7.0, 1e-12).astype(np.float32)
    q = np.clip(np.rint(grp / scale), -8, 7).astype(np.int8)
    return QTensor4(
        q=jnp.asarray(pack_int4(q.reshape(wf.shape))),
        scale=jnp.asarray(np.squeeze(scale, axis=-2)),
    )


def deq(w: Any, dtype) -> jnp.ndarray:
    """Dequantize at the einsum callsite (fused by XLA); pass-through otherwise."""
    if isinstance(w, QTensor):
        return (w.q.astype(jnp.float32) * w.scale).astype(dtype)
    if isinstance(w, QTensor4):
        vals = unpack_int4(w.q).astype(jnp.float32)  # [..., dim, O]
        dim, out_dim = vals.shape[-2], vals.shape[-1]
        G = w.scale.shape[-2]
        grp = vals.reshape(vals.shape[:-2] + (G, dim // G, out_dim))
        grp = grp * w.scale[..., :, None, :]
        return grp.reshape(vals.shape).astype(dtype)
    return w


def qeinsum(pattern: str, x: jnp.ndarray, w: Any, dtype) -> jnp.ndarray:
    """``einsum(pattern, x, w)`` with the dequant moved PAST the dot.

    int8: per-output-channel scales commute with the contraction:
    ``x @ (q * scale) == (x @ q) * scale`` exactly (scale is constant along
    the contracted axis).  The matmul's weight operand is then a PURE int8->
    dtype convert, which XLA folds into the dot's operand load — whereas the
    convert-*multiply* producer of :func:`deq` can materialize a full-width
    dequantized copy and drag the int8 path back to bf16 byte traffic.

    int4 (grouped): the scale varies along the contraction, so it commutes
    only past each GROUP's partial dot — the contraction splits as
    ``x[..., G, g] . q[..., G, g, O] -> partial[..., G, O]``, the per-group
    scale multiplies the partials, and the group axis sums last.  Exactly
    equal (up to float reassociation) to the dequantized dot, with the
    weight operand still an integer load.

    Valid whenever ``w``'s contraction axis is -2 and its last axis is the
    einsum output's last axis (true for every dense projection in
    models/llama.py).  Non-quantized weights pass straight through.
    """
    if isinstance(w, QTensor4):
        xs, rest = pattern.split(",")
        ws, os_ = rest.split("->")
        if not (xs[-1] == ws[-2] and ws[-1] == os_[-1]):
            # pattern outside the [..., in, out] contract: fall back to the
            # dequantized reference (correct, just not integer-read)
            return jnp.einsum(pattern, x, deq(w, dtype))
        vals = unpack_int4(w.q).astype(dtype)  # [..., dim, O]
        dim, out_dim = vals.shape[-2], vals.shape[-1]
        G = w.scale.shape[-2]
        grp_w = vals.reshape(vals.shape[:-2] + (G, dim // G, out_dim))
        grp_x = x.reshape(x.shape[:-1] + (G, dim // G))
        # 'G'/'z' are free letters: model patterns only use lowercase b/s/e/
        # f/o/v/x/c.  partial: contract within each group; then scale+sum G.
        partial = jnp.einsum(
            f"{xs[:-1]}Gz,{ws[:-2]}Gz{ws[-1]}->{os_[:-1]}G{os_[-1]}",
            grp_x,
            grp_w,
        )
        return jnp.einsum(
            f"{os_[:-1]}G{os_[-1]},{ws[:-2]}G{ws[-1]}->{os_}",
            partial,
            w.scale.astype(dtype),
        )
    if not isinstance(w, QTensor):
        return jnp.einsum(pattern, x, w)
    y = jnp.einsum(pattern, x, w.q.astype(dtype))
    return y * jnp.squeeze(w.scale, axis=-2).astype(dtype)


def quantize_fp8(x: jnp.ndarray, axis: int = -1, dtype=jnp.float8_e4m3fn):
    """Symmetric per-vector fp8 quantization along ``axis`` -> ``(q, scale)``.

    Used by the fp8 in-dot attention path (ops/attention.py ``fp8_dot``) to
    bring the QUERY operand down to the KV pool's storage width so the QK dot
    runs fp8 x fp8 with f32 accumulation — the same scale-on-partials
    discipline as :func:`qeinsum`: the f32 scale multiplies the dot's f32
    output, never the fp8 operand.  ``scale`` keeps the reduced axis with
    size 1 so it broadcasts back over the partials."""
    # host-side format constant (finfo is dtype metadata, not a device value;
    # np.finfo rejects the fp8 classes, ml_dtypes.finfo covers them)
    fmax = float(ml_dtypes.finfo(dtype).max)
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / fmax, 1e-12).astype(jnp.float32)
    q = (x.astype(jnp.float32) / scale).astype(dtype)
    return q, scale


def quantize_decoder_params(
    params: Dict[str, Any],
    fmt: str = "int8",
    group_size: int = INT4_GROUP_SIZE,
) -> Dict[str, Any]:
    """Quantize every layer projection; norms/biases/embeddings/head stay bf16
    (tiny, and embedding/head quality is disproportionately sensitive).

    ``fmt``: "int8" (per-channel, the default/back-compat path) or "int4"
    (per-group, ``group_size`` along the contraction axis)."""
    if fmt not in ("int8", "int4"):
        raise ValueError(f"unknown quantization format {fmt!r}")
    layers = dict(params["layers"])
    for key in QUANTIZABLE:
        if key in layers:
            layers[key] = (
                quantize_tensor_int4(layers[key], group_size)
                if fmt == "int4"
                else quantize_tensor(layers[key])
            )
    out = dict(params)
    out["layers"] = layers
    return out


def num_weights(params: Any) -> int:
    """Model weight count with packed formats unpacked (QTensor4 packs two
    weights per stored byte) and quantization scales excluded — the honest
    denominator-free N for MFU math (2 FLOPs/weight/token)."""
    import jax

    total = 0

    def is_q(x):
        return isinstance(x, (QTensor, QTensor4))

    for leaf in jax.tree.leaves(params, is_leaf=is_q):
        if isinstance(leaf, QTensor4):
            total += 2 * leaf.q.size
        elif isinstance(leaf, QTensor):
            total += leaf.q.size
        else:
            total += leaf.size
    return total


def weight_bits(params: Any) -> int:
    """Dominant layer-projection weight width in bits (4 / 8 / 16) — the
    operator gauge behind ``tick_stats``/``/metrics`` ``weight_bits``."""
    layers = params.get("layers", params) if isinstance(params, dict) else params
    leaves = layers.values() if isinstance(layers, dict) else [layers]
    bits = 16
    for leaf in leaves:
        if isinstance(leaf, QTensor4):
            return 4
        if isinstance(leaf, QTensor):
            bits = 8
    return bits
