"""Weight-only int8 quantization for the decode path.

Decode is HBM-bandwidth-bound: every generated token re-reads all layer
weights, so halving the bytes (bf16 -> int8 + per-channel f32 scales) nearly
doubles the decode roofline on real hardware and halves host->HBM transfer at
load.  The reference has no quantization (torch fp16 generate,
assistant/ai/providers/transformers.py:22-29); this is a TPU-first extra.

Scheme: symmetric per-output-channel.  Every projection weight in this
codebase is laid out ``[..., in, out]`` with the contraction on axis -2
(layer-stacked: wq/wk/wv [L,E,O], wo [L,O,E], MLP [L,(X,)E,F] / [L,(X,)F,E]),
so one rule quantizes them all: ``scale = max|w| over axis -2 / 127``.

``QTensor`` is a NamedTuple (automatically a pytree): the scale keeps the
weight's rank with the contracted dim = 1, so it scans along the layer axis
with the weights AND accepts the same PartitionSpec — ``shard_pytree``'s
sharding tree applies to a QTensor node as a pytree prefix, no rule changes.

Dequantization sits inside the einsum callsites (:func:`deq`); XLA fuses the
convert-multiply into the dot, so the bf16 weights are never materialized in
HBM — int8 is what gets read.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple

import jax.numpy as jnp
import numpy as np

QUANTIZABLE = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


class QTensor(NamedTuple):
    q: jnp.ndarray      # int8, original shape
    scale: jnp.ndarray  # f32, same rank, contracted (-2) dim = 1


def quantize_tensor(w) -> QTensor:
    """Symmetric per-output-channel int8 over contraction axis -2.

    Runs on HOST numpy: an on-device f32 upcast of a layer-stacked weight
    would double the bf16 footprint on one chip at exactly the moment
    quantization is supposed to shrink it.  shard_pytree transfers the int8
    result afterwards."""
    wf = np.asarray(w, np.float32)
    amax = np.max(np.abs(wf), axis=-2, keepdims=True)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.clip(np.rint(wf / scale), -127, 127).astype(np.int8)
    return QTensor(q=q, scale=scale)


def deq(w: Any, dtype) -> jnp.ndarray:
    """Dequantize at the einsum callsite (fused by XLA); pass-through otherwise."""
    if isinstance(w, QTensor):
        return (w.q.astype(jnp.float32) * w.scale).astype(dtype)
    return w


def qeinsum(pattern: str, x: jnp.ndarray, w: Any, dtype) -> jnp.ndarray:
    """``einsum(pattern, x, w)`` with the dequant moved PAST the dot.

    Per-output-channel scales commute with the contraction:
    ``x @ (q * scale) == (x @ q) * scale`` exactly (scale is constant along
    the contracted axis).  The matmul's weight operand is then a PURE int8->
    dtype convert, which XLA folds into the dot's operand load — whereas the
    convert-*multiply* producer of :func:`deq` can materialize a full-width
    dequantized copy and drag the int8 path back to bf16 byte traffic.

    Valid whenever ``w``'s last axis is the einsum output's last axis (true
    for every dense projection in models/llama.py).  Non-quantized weights
    pass straight through to a plain einsum.
    """
    if not isinstance(w, QTensor):
        return jnp.einsum(pattern, x, w)
    y = jnp.einsum(pattern, x, w.q.astype(dtype))
    return y * jnp.squeeze(w.scale, axis=-2).astype(dtype)


def quantize_decoder_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """Quantize every layer projection; norms/biases/embeddings/head stay bf16
    (tiny, and embedding/head quality is disproportionately sensitive)."""
    layers = dict(params["layers"])
    for key in QUANTIZABLE:
        if key in layers:
            layers[key] = quantize_tensor(layers[key])
    out = dict(params)
    out["layers"] = layers
    return out
