"""TPU-first neural-net ops.

The hot compute the reference delegates to torch (``model()`` forward and
``model.generate`` in assistant/ai/embedders/transformers.py and
assistant/ai/providers/transformers.py) lives here as jit-friendly JAX ops:
fused-by-XLA norms and RoPE, a pallas flash-attention kernel for TPU (with a pure-jnp
fallback used on CPU/in tests), shape-static nucleus sampling, and ring attention for
sequence/context parallelism over the mesh ``seq`` axis.
"""

from .norms import layer_norm, rms_norm  # noqa: F401
from .rope import apply_rope, rope_frequencies  # noqa: F401
from .attention import dot_product_attention, flash_attention  # noqa: F401
from .sampling import sample_logits  # noqa: F401
from .ring_attention import ring_attention  # noqa: F401
