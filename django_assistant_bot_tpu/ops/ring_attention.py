"""Ring attention — sequence/context parallelism over the mesh ``seq`` axis.

The reference *bounds* context to 8k tokens instead of scaling it (SURVEY.md §5.7,
reference: assistant/ai/providers/*.py ``context_size = 8000``).  Here long context is
first-class: the sequence dimension is sharded over the ``seq`` mesh axis and K/V
chunks rotate around the ICI ring via ``lax.ppermute`` while each device accumulates
blockwise online-softmax statistics — attention memory stays O(S/n) per chip and the
K/V transfers overlap with the per-chunk matmuls (XLA overlaps the ppermute DMA with
compute since the loop body's matmul does not depend on the incoming chunk).

Causal variant skips fully-masked chunk pairs' contributions via masking (compute is
still uniform per step — predictable ICI schedule beats raggedness on TPU).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..parallel.mesh import SEQ_AXIS
from .attention import NEG_INF


def _ring_body(q, k, v, axis_name: str, *, causal: bool):
    """Per-device blockwise attention with rotating K/V.  Shapes: [B,H,Sl,D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    B, H, Sl, D = q.shape
    scale = D ** -0.5
    qf = q.astype(jnp.float32) * scale

    m = jnp.full((B, H, Sl, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((B, H, Sl, 1), dtype=jnp.float32)
    o = jnp.zeros((B, H, Sl, D), dtype=jnp.float32)

    def step(i, carry):
        m, l, o, k_cur, v_cur = carry
        src_idx = (my_idx - i) % axis_size  # which shard's K/V we hold this step
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            qpos = my_idx * Sl + jax.lax.broadcasted_iota(jnp.int32, (Sl, Sl), 0)
            kpos = src_idx * Sl + jax.lax.broadcasted_iota(jnp.int32, (Sl, Sl), 1)
            s = jnp.where((qpos >= kpos)[None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        o_new = alpha * o + jnp.einsum("bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return m_new, l_new, o_new, k_nxt, v_nxt

    m, l, o, _, _ = jax.lax.fori_loop(0, axis_size, step, (m, l, o, k, v))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention(
    q: jnp.ndarray,  # [B, H, S, D] with S sharded over `seq`
    k: jnp.ndarray,
    v: jnp.ndarray,
    mesh: Mesh,
    *,
    causal: bool = False,
    axis_name: str = SEQ_AXIS,
) -> jnp.ndarray:
    """shard_map'd ring attention.  q/k/v sequence dims must be divisible by the
    ``seq`` axis size; batch rides ``data`` untouched."""
    spec = P(None, None, axis_name, None)
    from ..parallel.sharding import compat_shard_map

    fn = compat_shard_map(
        functools.partial(_ring_body, axis_name=axis_name, causal=causal),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
