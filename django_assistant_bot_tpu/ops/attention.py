"""Attention: pallas flash kernel for TPU + a jnp reference path.

Replaces the torch SDPA the reference reaches through ``AutoModel`` forwards
(reference: assistant/ai/embedders/transformers.py:15-29, providers/transformers.py:35-94).

Two paths, one contract:

- :func:`dot_product_attention` — pure jnp, f32 accumulation.  Used on CPU, in tests,
  and for short decode steps where the MXU is already saturated by the projections.
- :func:`flash_attention` — pallas TPU kernel, blocked online-softmax so the [S, S]
  score matrix never materialises in HBM (O(S) memory; the win for long prefill).

Both take ``[batch, heads, seq, head_dim]`` and support causal masking and GQA
(kv heads broadcast by the caller via repeat — XLA dedups the memory).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .quant import quantize_fp8

NEG_INF = -1e30

_FP8_DTYPES = (jnp.float8_e4m3fn, jnp.float8_e5m2)


def _check_fp8_dot(kv_dtype, site: str) -> None:
    if not any(jnp.dtype(kv_dtype) == jnp.dtype(t) for t in _FP8_DTYPES):
        raise ValueError(
            f"{site}: fp8_dot=True requires an fp8 KV cache "
            f"(float8_e4m3fn / float8_e5m2), got {kv_dtype}"
        )


def dot_product_attention(
    q: jnp.ndarray,  # [B, H, Sq, D]
    k: jnp.ndarray,  # [B, H, Sk, D]
    v: jnp.ndarray,  # [B, H, Sk, D]
    *,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,  # broadcastable to [B, H, Sq, Sk]; True=keep
    q_offset: int | jnp.ndarray = 0,  # absolute position of q[0] (decode w/ KV cache)
    window: Optional[int] = None,  # sliding window: keep iff kpos > qpos - window
) -> jnp.ndarray:
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal or window is not None:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2])
        keep = jnp.ones((q.shape[2], k.shape[2]), bool)
        if causal:
            keep &= qpos[:, None] >= kpos[None, :]
        if window is not None:
            # HF sliding-window semantics (masking_utils.sliding_window_overlay):
            # a query attends to the `window` most recent positions incl. itself
            keep &= kpos[None, :] > qpos[:, None] - window
        scores = jnp.where(keep[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def gqa_dot_product_attention(
    q: jnp.ndarray,  # [B, H, Sq, D]
    k: jnp.ndarray,  # [B, KH, Sk, D] — KV heads NOT repeated
    v: jnp.ndarray,  # [B, KH, Sk, D]
    *,
    mask: Optional[jnp.ndarray] = None,  # broadcastable to [B, 1, Sq, Sk]; True=keep
) -> jnp.ndarray:
    """Grouped-query attention that contracts query groups against the shared
    KV heads directly — no ``repeat(q_per_kv)`` materialization.

    On the decode path the repeat is the single biggest memory consumer: a
    [B, KH, S, D] slot cache repeated to H heads writes+reads q_per_kv x the
    cache bytes EVERY step (multi-GB of pure copy traffic at serving shapes).
    Grouping the einsum reads the cache once.
    """
    B, H, Sq, D = q.shape
    KH = k.shape[1]
    G = H // KH
    scale = D ** -0.5
    if k.dtype != q.dtype:
        # reduced-precision KV cache (e.g. fp8): a pure convert on the matmul
        # operand — fused into the dot, so the cache is READ at its own width
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    qg = q.reshape(B, KH, G, Sq, D)
    scores = jnp.einsum(
        "bkgqd,bksd->bkgqs", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        m = mask[:, :, None] if mask.ndim == 4 else mask  # insert group axis
        scores = jnp.where(m, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqs,bksd->bkgqd", probs, v)
    return out.reshape(B, H, Sq, D)


def chunked_gqa_decode_attention(
    q: jnp.ndarray,  # [B, H, 1, D]
    k: jnp.ndarray,  # [B, KH, S, D] slot cache, storage dtype (bf16 / fp8)
    v: jnp.ndarray,  # [B, KH, S, D]
    positions: jnp.ndarray,  # [B] int32 — absolute position of each slot's query
    *,
    chunk: int,
    active: Optional[jnp.ndarray] = None,  # [B] bool; inactive rows don't widen the read
    window: Optional[int] = None,
    fp8_dot: bool = False,
) -> jnp.ndarray:
    """Length-aware decode attention: read the slot cache in fixed ``chunk``-wide
    slices and SKIP every chunk past the batch's maximum valid position.

    The static-shape decode path otherwise reads the whole allocated
    ``[B, KH, S, D]`` cache every step — at 16k–32k allocated contexts serving
    short/ragged traffic, most of that bandwidth is spent on invalid positions
    (PERF.md's byte ledger: the KV read rivals the weights).  Here the chunk
    count actually read is a *traced* ``fori_loop`` bound derived from
    ``positions`` — one compiled program for every fill level (the "buckets"
    are chunk multiples), no dynamic shapes, no recompiles.  Per-slot validity
    inside the boundary chunk is handled by masking, exactly like the full
    read.

    Reduced-precision caches dequantize PER CHUNK: the ``astype`` sits on the
    sliced operand inside the loop body, so XLA reads fp8 from HBM and upcasts
    in registers/VMEM — never materializing a bf16-sized copy of the cache
    (the fix for the fp8-KV bandwidth regression, VERDICT r5 #2).

    Numerics: online softmax (flash discipline) with f32 running max/sum/acc —
    equal to the full-cache softmax up to reduction order (tested to per-dtype
    tolerance across ragged lengths and chunk boundaries).  A row whose band
    starts past the first processed chunk self-corrects: its all-masked chunks
    contribute with ``m = -inf`` and are zeroed by ``alpha = exp(-inf - m_new)``
    once a live chunk arrives.

    ``fp8_dot`` (docs/QUANT.md "fp8 in-dot"): keep the fp8 cache operand in
    its storage dtype THROUGH the QK dot instead of upcasting first.  The
    query is quantized to the cache's fp8 format once, outside the loop, and
    its per-(kv-head, group) f32 scale multiplies the f32 score partials —
    the same scale-on-partials discipline as the int4 ``qeinsum`` (the cache
    side's per-page scale is 1.0 by the storage contract, so only the query
    scale appears).  The PV dot likewise runs with fp8 probabilities against
    the fp8 values; the softmax normalizer ``l`` stays computed from the f32
    probabilities, matching the baseline's discipline.
    """
    B, H, Sq, D = q.shape
    if Sq != 1:
        raise ValueError(f"decode attention expects Sq=1 queries, got {Sq}")
    KH = k.shape[1]
    S = k.shape[2]
    if S % chunk:
        raise ValueError(f"chunk={chunk} must divide cache length {S}")
    G = H // KH
    scale = D ** -0.5
    if active is None:
        active = jnp.ones((B,), bool)
    qg = q.reshape(B, KH, G, D)
    if fp8_dot:
        _check_fp8_dot(k.dtype, "chunked_gqa_decode_attention")
        # quantize the query once, outside the chunk loop: [B, KH, G, D] fp8
        # plus a [B, KH, G, 1] f32 scale that rides on the score partials
        qg_q, qg_s = quantize_fp8(qg, axis=-1, dtype=k.dtype)

    # chunks [lo, hi) cover every active row's valid keys; inactive rows are
    # excluded so one stale long slot can't widen a short batch's read window
    act_pos = jnp.where(active, positions, 0)
    hi = jnp.max(act_pos) // chunk + 1
    if window is not None:
        # lowest key any active row may see: its position - window + 1
        min_pos = jnp.min(jnp.where(active, positions, S))
        lo = jnp.minimum(jnp.maximum(min_pos - window + 1, 0) // chunk, hi)
    else:
        lo = jnp.zeros((), hi.dtype)

    def body(ci, carry):
        m, l, acc = carry
        start = ci * chunk
        k_blk = jax.lax.dynamic_slice(k, (0, 0, start, 0), (B, KH, chunk, D))
        v_blk = jax.lax.dynamic_slice(v, (0, 0, start, 0), (B, KH, chunk, D))
        if fp8_dot:
            # in-dot fp8: both operands stay at storage width through the
            # MXU; the query's f32 scale multiplies the f32 partials
            s = jnp.einsum(
                "bkgd,bksd->bkgs", qg_q, k_blk,
                preferred_element_type=jnp.float32,
            ) * (qg_s * scale)  # [B, KH, G, chunk]
        else:
            if k_blk.dtype != q.dtype:
                # per-chunk dequant: a pure convert on the sliced operand,
                # fused into the dot — the cache streams at its own width
                k_blk = k_blk.astype(q.dtype)
                v_blk = v_blk.astype(q.dtype)
            s = jnp.einsum(
                "bkgd,bksd->bkgs", qg, k_blk, preferred_element_type=jnp.float32
            ) * scale  # [B, KH, G, chunk]
        kpos = start + jnp.arange(chunk)
        keep = kpos[None, :] <= positions[:, None]  # [B, chunk]
        if window is not None:
            keep &= kpos[None, :] > positions[:, None] - window
        s = jnp.where(keep[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bkgs,bksd->bkgd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((B, KH, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KH, G, 1), jnp.float32)
    a0 = jnp.zeros((B, KH, G, D), jnp.float32)
    _, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, a0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out.reshape(B, H, 1, D)


def paged_gqa_decode_attention(
    q: jnp.ndarray,  # [B, H, 1, D]
    k_pool: jnp.ndarray,  # [P, KH, page, D] page pool, storage dtype (bf16 / fp8)
    v_pool: jnp.ndarray,  # [P, KH, page, D]
    block_tables: jnp.ndarray,  # [B, NB] int32 — physical page per logical block;
    #                             entries >= P mean "unallocated" (read masked)
    positions: jnp.ndarray,  # [B] int32 — absolute position of each slot's query
    *,
    active: Optional[jnp.ndarray] = None,  # [B] bool; inactive rows don't widen the read
    window: Optional[int] = None,
    fp8_dot: bool = False,
) -> jnp.ndarray:
    """Block-table variant of :func:`chunked_gqa_decode_attention`: the KV
    "row" of a slot is a chain of fixed-size pages scattered through a shared
    pool, resolved one gather per logical block.

    Chunk == page: the loop structure, masking, and online-softmax state are
    EXACTLY :func:`chunked_gqa_decode_attention`'s with ``chunk = page`` — so
    for pools whose pages mirror a contiguous cache's chunks the result is
    bit-identical (the byte-identity contract tests/test_kv_paging.py pins).
    Logical blocks past a row's allocation gather a clamped page whose keys
    are masked out (scores pinned to ``NEG_INF`` -> exact zero contribution,
    the same discipline the contiguous path applies to garbage positions).

    Reduced-precision pools dequantize PER PAGE: the ``astype`` sits on the
    gathered operand, so the pool streams from HBM at its own width — same
    placement as the contiguous path's per-chunk dequant.

    ``fp8_dot``: in-dot fp8 compute, exactly the contiguous path's scheme —
    the query is quantized to the pool's fp8 format once outside the page
    loop and its f32 scale multiplies the f32 score partials (per-page pool
    scale is 1.0 by the storage contract); the PV dot runs fp8 x fp8.
    ``paged_tree_attention`` deliberately keeps the dequant read: the verify
    forward is one tick amortized over K+1 tokens, so its attention dot is
    not the bandwidth bottleneck the per-step decode dot is.
    """
    B, H, Sq, D = q.shape
    if Sq != 1:
        raise ValueError(f"decode attention expects Sq=1 queries, got {Sq}")
    P, KH, page, _ = k_pool.shape
    NB = block_tables.shape[1]
    S = NB * page
    G = H // KH
    scale = D ** -0.5
    if active is None:
        active = jnp.ones((B,), bool)
    qg = q.reshape(B, KH, G, D)
    if fp8_dot:
        _check_fp8_dot(k_pool.dtype, "paged_gqa_decode_attention")
        qg_q, qg_s = quantize_fp8(qg, axis=-1, dtype=k_pool.dtype)

    act_pos = jnp.where(active, positions, 0)
    hi = jnp.minimum(jnp.max(act_pos) // page + 1, NB)
    if window is not None:
        min_pos = jnp.min(jnp.where(active, positions, S))
        lo = jnp.minimum(jnp.maximum(min_pos - window + 1, 0) // page, hi)
    else:
        lo = jnp.zeros((), hi.dtype)

    def body(ci, carry):
        m, l, acc = carry
        phys = jax.lax.dynamic_slice_in_dim(block_tables, ci, 1, axis=1)[:, 0]
        phys = jnp.clip(phys, 0, P - 1)  # sentinel rows read a live page, masked below
        k_blk = jnp.take(k_pool, phys, axis=0)  # [B, KH, page, D]
        v_blk = jnp.take(v_pool, phys, axis=0)
        if fp8_dot:
            s = jnp.einsum(
                "bkgd,bksd->bkgs", qg_q, k_blk,
                preferred_element_type=jnp.float32,
            ) * (qg_s * scale)  # [B, KH, G, page]
        else:
            if k_blk.dtype != q.dtype:
                k_blk = k_blk.astype(q.dtype)
                v_blk = v_blk.astype(q.dtype)
            s = jnp.einsum(
                "bkgd,bksd->bkgs", qg, k_blk, preferred_element_type=jnp.float32
            ) * scale  # [B, KH, G, page]
        kpos = ci * page + jnp.arange(page)
        keep = kpos[None, :] <= positions[:, None]  # [B, page]
        if window is not None:
            keep &= kpos[None, :] > positions[:, None] - window
        s = jnp.where(keep[:, None, None, :], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bkgs,bksd->bkgd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((B, KH, G, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KH, G, 1), jnp.float32)
    a0 = jnp.zeros((B, KH, G, D), jnp.float32)
    _, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, a0))
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out.reshape(B, H, 1, D)


def paged_tree_attention(
    q: jnp.ndarray,  # [B, H, T, D] — one query per speculation-tree node
    k_pool: jnp.ndarray,  # [P, KH, page, D] page pool, storage dtype
    v_pool: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, NB] int32; entries >= P unallocated
    lengths: jnp.ndarray,  # [B] int32 — verified prefix length per row
    tree_k: jnp.ndarray,  # [B, KH, T, D] — the tree's freshly-projected keys
    tree_v: jnp.ndarray,
    anc_mask: jnp.ndarray,  # [T, T] bool — anc_mask[t, u]: u ancestor-or-self of t
    depths: jnp.ndarray,  # [T] int32 node depths (root = 0)
    *,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Tree-query variant of :func:`paged_gqa_decode_attention` for the
    speculative verify forward: every tree node attends to the row's
    verified prefix (positions ``< lengths``, read IN PLACE from the page
    pool one block-table gather per logical block — never a materialised
    dense copy of the logical row) plus its own root-path ancestors through
    the tree's fresh K/V, processed as one final masked chunk in the same
    online-softmax stream.

    The page loop reuses the decode read's structure exactly (same loop
    bounds, same masking, same f32 running max/sum/acc); the tree chunk is
    one more update with the ancestor mask in place of the positional one.
    Reduced-precision pools dequantize per page like the decode path.
    """
    B, H, T, D = q.shape
    P, KH, page, _ = k_pool.shape
    NB = block_tables.shape[1]
    G = H // KH
    scale = D ** -0.5
    qg = q.reshape(B, KH, G, T, D)
    # pages [lo, hi) cover every row's verified prefix (lengths == 0 rows
    # read nothing from the pool; their tree self-attention keeps l > 0)
    hi = jnp.minimum((jnp.max(lengths) + page - 1) // page, NB)
    qpos = lengths[:, None] + depths[None, :]  # [B, T] query positions
    if window is not None:
        lo = jnp.minimum(
            jnp.maximum(jnp.min(lengths) - window + 1, 0) // page, hi
        )
    else:
        lo = jnp.zeros((), hi.dtype)

    def body(ci, carry):
        m, l, acc = carry
        phys = jax.lax.dynamic_slice_in_dim(block_tables, ci, 1, axis=1)[:, 0]
        phys = jnp.clip(phys, 0, P - 1)
        k_blk = jnp.take(k_pool, phys, axis=0)  # [B, KH, page, D]
        v_blk = jnp.take(v_pool, phys, axis=0)
        if k_blk.dtype != q.dtype:
            k_blk = k_blk.astype(q.dtype)
            v_blk = v_blk.astype(q.dtype)
        s = jnp.einsum(
            "bkgtd,bksd->bkgts", qg, k_blk, preferred_element_type=jnp.float32
        ) * scale  # [B, KH, G, T, page]
        kpos = ci * page + jnp.arange(page)
        keep = kpos[None, None, :] < lengths[:, None, None]  # [B, 1, page]
        if window is not None:
            keep = keep & (kpos[None, None, :] > qpos[:, :, None] - window)
        s = jnp.where(keep[:, None, None], s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bkgts,bksd->bkgtd", p.astype(v_blk.dtype), v_blk,
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((B, KH, G, T, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, KH, G, T, 1), jnp.float32)
    a0 = jnp.zeros((B, KH, G, T, D), jnp.float32)
    m, l, acc = jax.lax.fori_loop(lo, hi, body, (m0, l0, a0))
    # the tree itself, as the final online-softmax chunk: ancestor-masked
    # (root attends to itself, so every live query has l > 0 even at
    # lengths == 0)
    tk = tree_k.astype(q.dtype) if tree_k.dtype != q.dtype else tree_k
    tv = tree_v.astype(q.dtype) if tree_v.dtype != q.dtype else tree_v
    s = jnp.einsum(
        "bkgtd,bkud->bkgtu", qg, tk, preferred_element_type=jnp.float32
    ) * scale  # [B, KH, G, T, T]
    keep = anc_mask[None, :, :]  # [1, T, T]
    if window is not None:
        upos = lengths[:, None] + depths[None, :]  # [B, T] key positions
        keep = keep & (upos[:, None, :] > qpos[:, :, None] - window)
    s = jnp.where(keep[:, None, None], s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m - m_new)
    l = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
    acc = alpha * acc + jnp.einsum(
        "bkgtu,bkud->bkgtd", p.astype(tv.dtype), tv,
        preferred_element_type=jnp.float32,
    )
    out = (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)
    return out.reshape(B, H, T, D)


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    m_scr,
    l_scr,
    acc_scr,
    *,
    kv_len: int,
    block_kv: int,
    chunk_kv: int,
    causal: bool,
    q_block: int,
    window: "Optional[int]" = None,
):
    """One (batch*head, q-block, kv-chunk) program: online softmax, chunked KV.

    q_ref: [q_block, D]; k_ref/v_ref: [chunk_kv, D] — K/V stream through VMEM
    one CHUNK per grid step instead of residing whole-row (a [Sk, D] resident
    block caps context at ~8k before the 16 MB VMEM scoped-stack limit; the
    chunked pipeline scales to any Sk).  The online-softmax state (m, l, acc)
    lives in VMEM scratch across the kv-chunk grid dimension; o_ref is
    written once, on the final chunk.

    Inside a chunk the kv loop runs at ``block_kv`` granularity with the same
    skip logic as before: causal q-blocks stop at the diagonal, and ``window``
    (sliding-window attention, HF semantics) skips sub-blocks entirely below
    the band — O(S*W) compute for long windowed prefill.
    """
    qi = pl.program_id(1)
    ci = pl.program_id(2)
    num_chunks = pl.num_programs(2)

    @pl.when(ci == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # keep operands in their storage dtype (bf16): the MXU's fast path; accumulate
    # in f32 via preferred_element_type.  Scaling folds into the f32 scores.
    q = q_ref[:]
    scale = q.shape[-1] ** -0.5

    spc = chunk_kv // block_kv  # sub-blocks per chunk
    num_kv_blocks = kv_len // block_kv
    if causal:
        # only kv blocks up to and including the diagonal participate
        last_block = ((qi + 1) * q_block + block_kv - 1) // block_kv
        num_iter = jnp.minimum(num_kv_blocks, last_block)
    else:
        num_iter = num_kv_blocks
    if window is not None:
        # lowest key any query in this block may see: qpos_min - window + 1
        first_iter = jnp.maximum(0, qi * q_block - window + 1) // block_kv
    else:
        first_iter = 0
    # intersect the global [first_iter, num_iter) range with this chunk
    lo = jnp.maximum(first_iter, ci * spc) - ci * spc
    hi = jnp.minimum(num_iter, (ci + 1) * spc) - ci * spc

    def body(ki, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(ki * block_kv, block_kv), :]
        v_blk = v_ref[pl.ds(ki * block_kv, block_kv), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale  # [qb, kb]
        if causal or window is not None:
            qpos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_kv), 0)
            kpos = (ci * spc + ki) * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (q_block, block_kv), 1
            )
            keep = qpos >= kpos if causal else (qpos == qpos)
            if window is not None:
                keep &= kpos > qpos - window
            s = jnp.where(keep, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        o_new = alpha * o + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    m, l, o = jax.lax.fori_loop(
        lo, hi, body, (m_scr[:, :1], l_scr[:, :1], acc_scr[:])
    )
    m_scr[:, :1] = m
    l_scr[:, :1] = l
    acc_scr[:] = o

    @pl.when(ci == num_chunks - 1)
    def _finalize():
        o_ref[:] = (acc_scr[:] / jnp.maximum(l_scr[:, :1], 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit,
    static_argnames=(
        "causal", "block_q", "block_kv", "interpret", "window", "chunk_kv"
    ),
)
def flash_attention(
    q: jnp.ndarray,  # [B, H, Sq, D]
    k: jnp.ndarray,  # [B, H, Sk, D]
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: bool = False,
    window: Optional[int] = None,
    chunk_kv: Optional[int] = None,  # default: min(8192, Sk); tests force smaller
) -> jnp.ndarray:
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_kv = min(block_kv, Sk)
    if Sq % block_q or Sk % block_kv:
        raise ValueError(f"seq lens ({Sq},{Sk}) must be multiples of blocks ({block_q},{block_kv})")
    if block_q % 8 or block_kv % 8 or D % 128 and D != 64:
        # Mosaic requires (8,128)-tile-aligned loads; reject early with a clear error
        # instead of a deep compiler failure.  Callers pad to a bucket first.
        raise ValueError(
            f"flash_attention needs 8-aligned seq blocks and head_dim 64/128k, got "
            f"blocks=({block_q},{block_kv}), head_dim={D}; pad sequences to a multiple of 8"
        )

    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)

    # K/V stream through VMEM one chunk per grid step (double-buffered by the
    # pallas pipeline).  A whole-row [Sk, D] resident block dies at Sk=16k
    # (16 MB VMEM scoped-stack limit — measured 16.12M at exactly 16k/D=64);
    # 8192-wide chunks stay at the old kernel's single-chunk performance for
    # Sk <= 8k (measured: chunking 8k into 2048s cost ~33% — extra per-chunk
    # programs + causal upper-triangle fetches) while scaling to any context.
    if chunk_kv is None:
        # largest chunk <= 8192 that divides Sk into block multiples (a
        # drop straight to block_kv at e.g. Sk=12288 would mean 96 chunk
        # programs per q-block — per-chunk overhead far beyond the ~33%
        # measured at 2048-wide chunks)
        chunk_kv = min(8192, Sk)
        while Sk % chunk_kv or chunk_kv % block_kv:
            chunk_kv -= block_kv
    if Sk % chunk_kv or chunk_kv % min(block_kv, chunk_kv):
        raise ValueError(f"chunk_kv={chunk_kv} must divide Sk={Sk} into block multiples")
    kernel = functools.partial(
        _flash_kernel,
        kv_len=Sk,
        block_kv=min(block_kv, chunk_kv),
        chunk_kv=chunk_kv,
        causal=causal,
        q_block=block_q,
        window=window,
    )
    def kv_index(bh, qi, ci):
        # Clamp dead chunks onto the nearest live one: grid steps whose chunk
        # is entirely past the causal diagonal (or below the window band) run
        # zero kernel iterations, and mapping them to a repeated block index
        # makes the pallas pipeline SKIP the copy — without this, causal
        # prefill streams ~2x the live K/V bytes and windowed prefill loses
        # its O(S*W) traffic property.
        c = ci
        if causal:
            last = ((qi + 1) * block_q - 1) // chunk_kv
            c = jnp.minimum(c, last)
        if window is not None:
            first = jnp.maximum(0, qi * block_q - window + 1) // chunk_kv
            c = jnp.maximum(c, first)
        return (bh, c, 0)

    out = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // block_q, Sk // chunk_kv),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi, ci: (bh, qi, 0)),
            pl.BlockSpec((None, chunk_kv, D), kv_index),
            pl.BlockSpec((None, chunk_kv, D), kv_index),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qi, ci: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l (col 0 used)
            pltpu.VMEM((block_q, D), jnp.float32),  # acc
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Sq, D)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,
    q_offset: int | jnp.ndarray = 0,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Dispatch: pallas flash kernel on TPU for long un-masked sequences, jnp otherwise.

    Decode steps (Sq==1) and padded/masked batches use the jnp path — at those shapes
    the projections dominate and XLA's fused softmax is already bandwidth-optimal.
    ``window`` (sliding-window attention) rides the flash path: the kernel skips
    kv blocks below the band entirely.
    """
    D = q.shape[-1]
    use_flash = (
        jax.default_backend() == "tpu"
        and mask is None
        and q.shape[2] >= 256
        and q.shape[2] % 128 == 0
        and k.shape[2] % 128 == 0
        and (D == 64 or D % 128 == 0)
        and isinstance(q_offset, int)
        and q_offset == 0
    )
    if use_flash:
        return flash_attention(q, k, v, causal=causal, window=window)
    return dot_product_attention(
        q, k, v, causal=causal, mask=mask, q_offset=q_offset, window=window
    )
