"""Attention: pallas flash kernel for TPU + a jnp reference path.

Replaces the torch SDPA the reference reaches through ``AutoModel`` forwards
(reference: assistant/ai/embedders/transformers.py:15-29, providers/transformers.py:35-94).

Two paths, one contract:

- :func:`dot_product_attention` — pure jnp, f32 accumulation.  Used on CPU, in tests,
  and for short decode steps where the MXU is already saturated by the projections.
- :func:`flash_attention` — pallas TPU kernel, blocked online-softmax so the [S, S]
  score matrix never materialises in HBM (O(S) memory; the win for long prefill).

Both take ``[batch, heads, seq, head_dim]`` and support causal masking and GQA
(kv heads broadcast by the caller via repeat — XLA dedups the memory).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

NEG_INF = -1e30


def dot_product_attention(
    q: jnp.ndarray,  # [B, H, Sq, D]
    k: jnp.ndarray,  # [B, H, Sk, D]
    v: jnp.ndarray,  # [B, H, Sk, D]
    *,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,  # broadcastable to [B, H, Sq, Sk]; True=keep
    q_offset: int | jnp.ndarray = 0,  # absolute position of q[0] (decode w/ KV cache)
    window: Optional[int] = None,  # sliding window: keep iff kpos > qpos - window
) -> jnp.ndarray:
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal or window is not None:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2])
        keep = jnp.ones((q.shape[2], k.shape[2]), bool)
        if causal:
            keep &= qpos[:, None] >= kpos[None, :]
        if window is not None:
            # HF sliding-window semantics (masking_utils.sliding_window_overlay):
            # a query attends to the `window` most recent positions incl. itself
            keep &= kpos[None, :] > qpos[:, None] - window
        scores = jnp.where(keep[None, None], scores, NEG_INF)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def gqa_dot_product_attention(
    q: jnp.ndarray,  # [B, H, Sq, D]
    k: jnp.ndarray,  # [B, KH, Sk, D] — KV heads NOT repeated
    v: jnp.ndarray,  # [B, KH, Sk, D]
    *,
    mask: Optional[jnp.ndarray] = None,  # broadcastable to [B, 1, Sq, Sk]; True=keep
) -> jnp.ndarray:
    """Grouped-query attention that contracts query groups against the shared
    KV heads directly — no ``repeat(q_per_kv)`` materialization.

    On the decode path the repeat is the single biggest memory consumer: a
    [B, KH, S, D] slot cache repeated to H heads writes+reads q_per_kv x the
    cache bytes EVERY step (multi-GB of pure copy traffic at serving shapes).
    Grouping the einsum reads the cache once.
    """
    B, H, Sq, D = q.shape
    KH = k.shape[1]
    G = H // KH
    scale = D ** -0.5
    if k.dtype != q.dtype:
        # reduced-precision KV cache (e.g. fp8): a pure convert on the matmul
        # operand — fused into the dot, so the cache is READ at its own width
        k = k.astype(q.dtype)
        v = v.astype(q.dtype)
    qg = q.reshape(B, KH, G, Sq, D)
    scores = jnp.einsum(
        "bkgqd,bksd->bkgqs", qg, k, preferred_element_type=jnp.float32
    ) * scale
    if mask is not None:
        m = mask[:, :, None] if mask.ndim == 4 else mask  # insert group axis
        scores = jnp.where(m, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgqs,bksd->bkgqd", probs, v)
    return out.reshape(B, H, Sq, D)


# ---------------------------------------------------------------------------
# Pallas flash attention
# ---------------------------------------------------------------------------


def _flash_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *,
    kv_len: int,
    block_kv: int,
    causal: bool,
    q_block: int,
    window: "Optional[int]" = None,
):
    """One (batch*head, q-block) program: online softmax over kv blocks.

    q_ref: [q_block, D]; k_ref/v_ref: [Sk, D]; o_ref: [q_block, D].

    With ``window`` (sliding-window attention, HF semantics: a query attends to
    the ``window`` most recent positions including itself) the kv loop also
    SKIPS blocks entirely below the band — the memory-traffic win that makes
    long windowed prefill O(S*W) instead of O(S^2).
    """
    qi = pl.program_id(1)
    # keep operands in their storage dtype (bf16): the MXU's fast path; accumulate
    # in f32 via preferred_element_type.  Scaling folds into the f32 scores.
    q = q_ref[:]
    scale = q.shape[-1] ** -0.5

    m0 = jnp.full((q_block, 1), NEG_INF, dtype=jnp.float32)
    l0 = jnp.zeros((q_block, 1), dtype=jnp.float32)
    o0 = jnp.zeros((q_block, q.shape[-1]), dtype=jnp.float32)

    num_kv_blocks = kv_len // block_kv
    if causal:
        # only kv blocks up to and including the diagonal participate
        last_block = ((qi + 1) * q_block + block_kv - 1) // block_kv
        num_iter = jnp.minimum(num_kv_blocks, last_block)
    else:
        num_iter = num_kv_blocks
    if window is not None:
        # lowest key any query in this block may see: qpos_min - window + 1
        first_iter = jnp.maximum(0, qi * q_block - window + 1) // block_kv
    else:
        first_iter = 0

    def body(ki, carry):
        m, l, o = carry
        k_blk = k_ref[pl.ds(ki * block_kv, block_kv), :]
        v_blk = v_ref[pl.ds(ki * block_kv, block_kv), :]
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32) * scale  # [qb, kb]
        if causal or window is not None:
            qpos = qi * q_block + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_kv), 0)
            kpos = ki * block_kv + jax.lax.broadcasted_iota(jnp.int32, (q_block, block_kv), 1)
            keep = qpos >= kpos if causal else (qpos == qpos)
            if window is not None:
                keep &= kpos > qpos - window
            s = jnp.where(keep, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1, keepdims=True)
        o_new = alpha * o + jnp.dot(
            p.astype(v_blk.dtype), v_blk, preferred_element_type=jnp.float32
        )
        return m_new, l_new, o_new

    m, l, o = jax.lax.fori_loop(first_iter, num_iter, body, (m0, l0, o0))
    o_ref[:] = (o / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_kv", "interpret", "window")
)
def flash_attention(
    q: jnp.ndarray,  # [B, H, Sq, D]
    k: jnp.ndarray,  # [B, H, Sk, D]
    v: jnp.ndarray,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_kv: int = 128,
    interpret: bool = False,
    window: Optional[int] = None,
) -> jnp.ndarray:
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    block_q = min(block_q, Sq)
    block_kv = min(block_kv, Sk)
    if Sq % block_q or Sk % block_kv:
        raise ValueError(f"seq lens ({Sq},{Sk}) must be multiples of blocks ({block_q},{block_kv})")
    if block_q % 8 or block_kv % 8 or D % 128 and D != 64:
        # Mosaic requires (8,128)-tile-aligned loads; reject early with a clear error
        # instead of a deep compiler failure.  Callers pad to a bucket first.
        raise ValueError(
            f"flash_attention needs 8-aligned seq blocks and head_dim 64/128k, got "
            f"blocks=({block_q},{block_kv}), head_dim={D}; pad sequences to a multiple of 8"
        )

    qf = q.reshape(B * H, Sq, D)
    kf = k.reshape(B * H, Sk, D)
    vf = v.reshape(B * H, Sk, D)

    kernel = functools.partial(
        _flash_kernel,
        kv_len=Sk,
        block_kv=block_kv,
        causal=causal,
        q_block=block_q,
        window=window,
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, Sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
            pl.BlockSpec((None, Sk, D), lambda bh, qi: (bh, 0, 0)),
            pl.BlockSpec((None, Sk, D), lambda bh, qi: (bh, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda bh, qi: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Sq, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(B, H, Sq, D)


def attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    *,
    causal: bool = False,
    mask: Optional[jnp.ndarray] = None,
    q_offset: int | jnp.ndarray = 0,
    window: Optional[int] = None,
) -> jnp.ndarray:
    """Dispatch: pallas flash kernel on TPU for long un-masked sequences, jnp otherwise.

    Decode steps (Sq==1) and padded/masked batches use the jnp path — at those shapes
    the projections dominate and XLA's fused softmax is already bandwidth-optimal.
    ``window`` (sliding-window attention) rides the flash path: the kernel skips
    kv blocks below the band entirely.
    """
    D = q.shape[-1]
    use_flash = (
        jax.default_backend() == "tpu"
        and mask is None
        and q.shape[2] >= 256
        and q.shape[2] % 128 == 0
        and k.shape[2] % 128 == 0
        and (D == 64 or D % 128 == 0)
        and isinstance(q_offset, int)
        and q_offset == 0
    )
    if use_flash:
        return flash_attention(q, k, v, causal=causal, window=window)
    return dot_product_attention(
        q, k, v, causal=causal, mask=mask, q_offset=q_offset, window=window
    )
