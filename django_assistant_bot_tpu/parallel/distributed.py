"""Multi-host bootstrap — the NCCL/MPI-backend analog, the JAX way.

The reference has no collectives backend at all (SURVEY.md §2.3 — its
"distributed" substrate is Celery+Redis+HTTP).  Here the TPU compute plane scales
across hosts with ``jax.distributed``: one process per host joins the cluster
over DCN, ``jax.devices()`` becomes the GLOBAL device list, and the same
mesh/sharding code paths from :mod:`.mesh`/:mod:`.sharding` then span every slice
— XLA routes intra-slice collectives over ICI and inter-slice ones over DCN.

Environment contract (all optional — TPU pods auto-discover via the metadata
server, so ``initialize_cluster()`` with no args is the common case):

- ``DABT_COORDINATOR``   — ``host:port`` of process 0
- ``DABT_NUM_PROCESSES`` — world size
- ``DABT_PROCESS_ID``    — this process's rank

Mesh guidance for multi-host (scaling-book recipe): put ``data`` (and optionally
``expert``) on the DCN boundary — their collectives are per-step, not per-layer —
and keep ``model``/``seq`` inside a slice where ICI bandwidth is.
:func:`multihost_mesh` encodes that: axis order (data, seq, model, expert) with
``model`` innermost already groups neighbouring devices intra-host.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

from .mesh import MeshAxes, best_mesh_shape, make_mesh

logger = logging.getLogger(__name__)

_initialized = False


def initialize_cluster(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join (or form) the multi-host cluster.  Idempotent; no-op single-host."""
    global _initialized
    if _initialized:
        return
    coordinator_address = coordinator_address or os.environ.get("DABT_COORDINATOR")
    num_processes = num_processes or _int_env("DABT_NUM_PROCESSES")
    process_id = process_id if process_id is not None else _int_env("DABT_PROCESS_ID")
    if coordinator_address is None and num_processes is None:
        # TPU pod slices self-discover through the runtime; bare initialize()
        # is correct there.  On a single host it is a no-op.
        try:
            jax.distributed.initialize()
        except Exception as e:  # single-process environments raise; that's fine
            logger.debug("jax.distributed.initialize skipped: %s", e)
    else:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    _initialized = True
    logger.info(
        "cluster: process %d/%d, %d global / %d local devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
        len(jax.local_devices()),
    )


def _int_env(name: str) -> Optional[int]:
    value = os.environ.get(name)
    return int(value) if value is not None else None


def multihost_mesh(
    *,
    want_model: int = 1,
    want_seq: int = 1,
    want_expert: int = 1,
):
    """Global mesh over every device in the cluster (call after
    :func:`initialize_cluster`).  ``data`` gets the remainder, so adding hosts
    grows DP while TP/SP/EP stay intra-slice."""
    n = len(jax.devices())
    axes: MeshAxes = best_mesh_shape(
        n, want_model=want_model, want_seq=want_seq, want_expert=want_expert
    )
    return make_mesh(axes)


def is_primary() -> bool:
    """True on the process that should write checkpoints / serve admin."""
    return jax.process_index() == 0


# --------------------------------------------------------- fleet plane env
# The serving fleet (serving/fleet.py; docs/FLEET.md) is a SEPARATE plane
# from the jax.distributed compute cluster above: fleet peers are whole
# serve processes talking HTTP, not devices sharing a mesh.  Same env-var
# convention, though, so one launcher template configures both:
#
# - ``DABT_FLEET_SELF``  — this process's name on the fleet wire
# - ``DABT_FLEET_PEERS`` — ``name=url,name=url`` peer list


def fleet_self_name(explicit: Optional[str] = None) -> Optional[str]:
    """This process's fleet-wire name: the explicit CLI value wins, then
    DABT_FLEET_SELF, then None (FleetPlane defaults to proc-<pid>)."""
    if explicit:
        return explicit
    return os.environ.get("DABT_FLEET_SELF") or None


def fleet_peers_from_env(explicit: Optional[str] = None) -> list:
    """Parse ``name=url,name=url`` (the --fleet-peers flag, falling back to
    DABT_FLEET_PEERS) into ``[(name, url), ...]``.  A bare URL with no
    ``name=`` gets an index-derived name; empty entries are skipped."""
    raw = explicit if explicit is not None else os.environ.get("DABT_FLEET_PEERS", "")
    peers = []
    for i, part in enumerate(p.strip() for p in (raw or "").split(",")):
        if not part:
            continue
        if "=" in part:
            name, url = part.split("=", 1)
            name = name.strip() or f"peer{i}"
        else:
            name, url = f"peer{i}", part
        peers.append((name, url.strip()))
    return peers
