"""Device-mesh and sharding plane.

The reference has no device parallelism at all (SURVEY.md §2.3 — its "distributed"
substrate is Celery+Redis and HTTP). Here parallelism is first-class: every model in
:mod:`~django_assistant_bot_tpu.models` is defined against a named
:class:`jax.sharding.Mesh` with axes ``("data", "seq", "model", "expert")`` and XLA
collectives over ICI do the communication.
"""

from .mesh import (  # noqa: F401
    MeshAxes,
    best_mesh_shape,
    get_mesh,
    make_mesh,
    local_device_count,
)
from .sharding import (  # noqa: F401
    logical_to_pspec,
    named_sharding,
    shard_pytree,
    with_constraint,
)
from .slicing import (  # noqa: F401
    DeviceSlice,
    MeshPlanner,
    NoCapacity,
)
from .distributed import (  # noqa: F401
    initialize_cluster,
    is_primary,
    multihost_mesh,
)
