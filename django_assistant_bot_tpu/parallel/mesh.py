"""Mesh construction over TPU slices (and CPU fake meshes for tests).

Replaces the reference's device discovery ``get_torch_device()`` cuda->mps->cpu
(reference: assistant/ai/utils/transformers.py:9-22) with JAX mesh bootstrap: a single
code path that works on one chip, a v5e-8 slice, or an 8-device fake CPU mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=8``) used by the test suite.

Axis conventions (aligned with the scaling-book recipe):

- ``data``   — batch-dimension sharding (DP).  Collectives: psum of grads.
- ``seq``    — sequence/context parallelism (ring attention rides this axis over ICI).
- ``model``  — tensor parallelism of attention heads / MLP hidden (TP).
- ``expert`` — expert parallelism for MoE layers (EP); folded into ``model`` when the
  mesh is too small to give it its own axis.
- ``pipe``   — pipeline parallelism over layer spans (GPipe microbatch schedule via
  ``shard_map`` + ``ppermute``; parallel/pipeline.py).  Collectives: one activation
  ppermute per stage per microbatch step, riding neighbouring ICI links.
"""

from __future__ import annotations

import dataclasses
import math
import os
import threading
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

DATA_AXIS = "data"
SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"

AXIS_ORDER = (DATA_AXIS, SEQ_AXIS, MODEL_AXIS, EXPERT_AXIS, PIPE_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshAxes:
    """A concrete mesh shape over the four logical axes."""

    data: int = 1
    seq: int = 1
    model: int = 1
    expert: int = 1
    pipe: int = 1

    @property
    def total(self) -> int:
        return self.data * self.seq * self.model * self.expert * self.pipe

    def as_tuple(self) -> tuple[int, int, int, int, int]:
        return (self.data, self.seq, self.model, self.expert, self.pipe)


def local_device_count() -> int:
    return len(jax.devices())


def best_mesh_shape(
    n_devices: int,
    *,
    want_model: int = 1,
    want_seq: int = 1,
    want_expert: int = 1,
    want_pipe: int = 1,
) -> MeshAxes:
    """Choose a mesh shape for ``n_devices``: satisfy the requested model/seq/expert
    degrees (clamped to what divides ``n_devices``) and give the remainder to data.
    """
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")

    def clamp(want: int, available: int) -> int:
        want = max(1, min(want, available))
        while available % want != 0:
            want -= 1
        return want

    model = clamp(want_model, n_devices)
    rest = n_devices // model
    seq = clamp(want_seq, rest)
    rest //= seq
    expert = clamp(want_expert, rest)
    rest //= expert
    pipe = clamp(want_pipe, rest)
    rest //= pipe
    return MeshAxes(data=rest, seq=seq, model=model, expert=expert, pipe=pipe)


def make_mesh(
    axes: Optional[MeshAxes] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 4-axis :class:`Mesh`.

    Device order matters for ICI locality: ``model`` (the chattiest axis — per-layer
    all-reduces) is the innermost/fastest-varying axis so TP collectives ride
    neighbouring ICI links; ``data`` is outermost (gradient/batch collectives are the
    least frequent and can span DCN in multi-host deployments).
    """
    devices = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = best_mesh_shape(len(devices))
    if axes.total != len(devices):
        raise ValueError(
            f"Mesh shape {axes} needs {axes.total} devices, have {len(devices)}"
        )
    dev_array = np.array(devices).reshape(axes.as_tuple())
    return Mesh(dev_array, AXIS_ORDER)


_default_mesh_lock = threading.Lock()
_default_mesh: Optional[Mesh] = None


def get_mesh(
    *,
    want_model: Optional[int] = None,
    want_seq: int = 1,
    want_expert: int = 1,
    refresh: bool = False,
) -> Mesh:
    """Process-wide default mesh (lazily built, thread-safe).

    ``want_model`` defaults to the env var ``DABT_MODEL_PARALLEL`` or 1.  Serving code
    calls this once at startup; tests build explicit meshes via :func:`make_mesh`.
    """
    global _default_mesh
    with _default_mesh_lock:
        if _default_mesh is None or refresh:
            if want_model is None:
                want_model = int(os.environ.get("DABT_MODEL_PARALLEL", "1"))
            n = local_device_count()
            axes = best_mesh_shape(
                n, want_model=want_model, want_seq=want_seq, want_expert=want_expert
            )
            _default_mesh = make_mesh(axes)
        return _default_mesh


def pad_to_multiple(n: int, multiple: int) -> int:
    """Round ``n`` up to a multiple (used to keep batch/seq dims divisible by mesh axes
    and by the (8,128)/(16,128) TPU tile shapes)."""
    return int(math.ceil(n / multiple) * multiple)
