"""Pipeline parallelism: GPipe microbatch schedule as SPMD over a ``pipe`` mesh axis.

The one parallelism axis the framework lacked (SURVEY.md §2.3 group "PP/EP/SP";
the reference has no distributed execution at all — its scale story is N
gunicorn workers x full model replicas, gpu_service/gunicorn_conf.py:9).  PP is
what serves/trains a model DEEPER than one chip's HBM: each stage holds only
``L/P`` contiguous layers, so per-chip layer memory drops P-fold — orthogonal
to TP (which splits each layer wide) and DP (which splits the batch).

TPU-native formulation (scaling-book collective-pipelining recipe) — no
torch-style per-rank send/recv processes:

- ``params['layers']`` leaves ([L, ...]) shard their LAYER axis over ``pipe``:
  inside ``shard_map`` every stage sees a local ``[L/P, ...]`` span and runs it
  with :func:`~..models.llama.forward_layers`.
- The GPipe schedule is a ``lax.scan`` over ``T = M + P - 1`` clock ticks.  At
  tick ``t`` stage ``s`` works on microbatch ``t - s``; between ticks the
  activation block moves to the next stage with ONE ``ppermute`` hop riding
  neighbouring ICI links (``pipe`` is the innermost mesh axis — mesh.py).
- Stages run one identical SPMD program: stage 0 *injects* (selects its own
  embedding output over the rotated-in activation), the last stage *collects*
  per-microbatch logits.  Embedding/norm/head weights are replicated over
  ``pipe`` (at depth P the layer span dominates memory; placing embed/head on
  the edge stages is a further refinement the sharding spec localises here).
- Backward is just ``jax.grad`` THROUGH the scan+ppermute (the transpose of a
  ppermute is the reverse ppermute): XLA derives the reverse schedule, no
  hand-written 1F1B.  Replicated-leaf gradients are psum'd over ``pipe``
  explicitly; layer-span gradients stay local to their stage.

Bubble fraction is the GPipe ``(P-1)/(M+P-1)`` — callers pick ``n_micro >> P``
to amortise.  Full causal attention families only (forward_layers); windowed
families bound their own context instead.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax
from .sharding import compat_shard_map as shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import llama
from ..models.config import DecoderConfig
from .mesh import DATA_AXIS, PIPE_AXIS

Params = Any


def pipeline_param_specs(cfg: DecoderConfig, params: Params) -> Params:
    """PartitionSpec tree: layer-stacked leaves shard axis 0 over ``pipe``,
    everything else (embed/head/norms) replicates."""

    def spec_for(path, leaf):
        # params['layers'] subtree: leading axis is the layer axis
        return P(PIPE_AXIS) if path[0].key == "layers" else P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def _check(cfg: DecoderConfig, mesh: Mesh, n_micro: int, batch: int, seq: int):
    n_stages = mesh.shape[PIPE_AXIS]
    if n_stages < 2:
        raise ValueError(f"pipeline needs a pipe axis >= 2, mesh has {n_stages}")
    if cfg.num_layers % n_stages != 0:
        raise ValueError(
            f"num_layers={cfg.num_layers} must divide over {n_stages} stages"
        )
    if llama._window_split(cfg) < cfg.num_layers:
        raise NotImplementedError(
            "pipeline parallelism supports full causal attention only "
            "(sliding-window layer indices are absolute, a stage span is not)"
        )
    if batch % n_micro != 0:
        raise ValueError(f"batch={batch} must divide into n_micro={n_micro}")
    dp = mesh.shape[DATA_AXIS]
    if (batch // n_micro) % dp != 0:
        raise ValueError(
            f"microbatch size {batch // n_micro} must divide over data axis {dp}"
        )
    return n_stages


def pipeline_forward(
    params: Params,
    cfg: DecoderConfig,
    input_ids: jnp.ndarray,  # [B, S]
    mesh: Mesh,
    *,
    n_micro: int,
) -> jnp.ndarray:
    """Pipeline-parallel forward -> logits [B, S, V] f32.

    Semantics match :func:`~..models.llama.forward` exactly (tested against it);
    only the execution schedule differs.
    """
    B, S = input_ids.shape
    n_stages = _check(cfg, mesh, n_micro, B, S)

    def spmd(layer_span, rest, ids_mb):
        # layer_span: [L/P, ...] local span;  ids_mb: [M, B/M/dp, S]
        logits_mb = _gpipe_schedule(layer_span, rest, ids_mb, cfg, n_stages, n_micro)
        return logits_mb  # [M, B/M/dp, S, V]

    layers = params["layers"]
    rest = {k: v for k, v in params.items() if k != "layers"}
    ids_mb = input_ids.reshape(n_micro, B // n_micro, S)

    # the body runs fully manual — suppress the model code's logical-axis
    # constraints while it traces (older jax rejects them at lowering)
    from .sharding import constraints_disabled

    with constraints_disabled():
        out = shard_map(
            spmd,
            mesh=mesh,
            in_specs=(P(PIPE_AXIS), P(), P(None, DATA_AXIS)),
            out_specs=P(None, DATA_AXIS),
            check_vma=False,
        )(layers, rest, ids_mb)
    return out.reshape(B, S, -1)


def _gpipe_schedule(layer_span, rest, ids_mb, cfg, n_stages, n_micro):
    """The per-device GPipe clock: runs inside shard_map.

    ``layer_span`` is this stage's [L/P, ...] layers; ``ids_mb`` [M, b, S] is
    the full microbatch queue (replicated over ``pipe``).  Returns the last
    stage's logits for every microbatch, psum'd over ``pipe`` so each device
    holds the full [M, b, S, V] result (zeros from non-final stages).
    """
    M = n_micro
    b, S = ids_mb.shape[1], ids_mb.shape[2]
    stage = jax.lax.axis_index(PIPE_AXIS)
    cos, sin = llama._rope_tables(cfg, S)
    is_first = stage == 0
    is_last = stage == n_stages - 1

    def tick(carry, t):
        state = carry  # [b, S, E]: the activation this stage holds
        # stage 0 injects microbatch t (clamped index; past-M ticks feed
        # garbage that never reaches a collect — schedule masks it out)
        inject = llama._embed({"tok_embed": rest["tok_embed"]}, cfg, ids_mb[jnp.minimum(t, M - 1)])
        x = jnp.where(is_first, inject, state)
        x = llama.forward_layers(layer_span, cfg, x, cos, sin)
        # the last stage finishes microbatch m = t - (P-1) at tick t; collect
        # the E-wide ACTIVATION, not logits — the final-norm+head runs once
        # after the scan, so the [*, V] tensor (the largest in training at a
        # 128k vocab) is neither computed P times per tick nor psum'd
        # pipe-wide (r4 advisor finding)
        m = t - (n_stages - 1)
        collect = (is_last & (m >= 0)).astype(x.dtype)
        out_t = (x * collect, jnp.maximum(m, 0))
        # rotate activations one stage forward (P-1 -> 0 carries garbage that
        # stage 0 overwrites by injecting)
        nxt = jax.lax.ppermute(
            x, PIPE_AXIS, [(i, (i + 1) % n_stages) for i in range(n_stages)]
        )
        return nxt, out_t

    state0 = jnp.zeros((b, S, cfg.hidden_size), cfg.dtype)
    _, (outs, ms) = jax.lax.scan(
        tick, state0, jnp.arange(M + n_stages - 1), length=M + n_stages - 1
    )
    # scatter the T collected slots into [M, ...] (non-collect ticks wrote
    # zeros at m=0; summing with the one real m=0 entry keeps it intact only
    # if the zeros stay zero — they do, `collect` zeroes whole blocks)
    acts_mb = jnp.zeros((M, b, S, cfg.hidden_size), outs.dtype)
    acts_mb = acts_mb.at[ms].add(outs)
    # only the final stage holds real values; psum replicates them pipe-wide
    # (E-wide — V/E-fold less collective traffic than psum'ing logits)
    acts_mb = jax.lax.psum(acts_mb, PIPE_AXIS)
    # final norm + shared head projection (handles int8 QTensor tables too),
    # applied ONCE over all microbatches
    normed = llama.rms_norm(acts_mb, rest["final_norm"], cfg.rms_norm_eps)
    return llama._head_logits(rest, cfg, normed).astype(jnp.float32)


def pipeline_loss(
    params: Params,
    cfg: DecoderConfig,
    input_ids: jnp.ndarray,
    loss_mask: jnp.ndarray,
    mesh: Mesh,
    *,
    n_micro: int,
) -> jnp.ndarray:
    """Next-token cross-entropy through the pipeline schedule (== train.lm_loss)."""
    logits = pipeline_forward(params, cfg, input_ids, mesh, n_micro=n_micro)
    targets = input_ids[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None].astype(jnp.int32), axis=-1)[..., 0]
    mask = loss_mask[:, 1:].astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def init_pipeline_state(cfg: DecoderConfig, optimizer, *, rng, mesh: Mesh):
    """Init params + opt state with layers sharded over ``pipe`` (and the
    usual logical TP axes inert — PP composes with DP here; PP x TP would
    shard the span leaves' head/mlp axes too)."""
    from ..training.train import TrainState

    params = llama.init(cfg, rng)
    specs = pipeline_param_specs(cfg, params)
    params = jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )
    opt_state = optimizer.init(params)
    return TrainState(params=params, opt_state=opt_state, step=0)


def make_pipeline_train_step(
    cfg: DecoderConfig,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    *,
    n_micro: int,
):
    """jit-able (params, opt_state, ids, mask) -> (params, opt_state, metrics).

    Gradients flow through the scan+ppermute schedule (XLA derives the reverse
    pipeline); the optimizer update is ordinary optax on the sharded trees.
    """

    def step(params, opt_state, input_ids, loss_mask):
        loss, grads = jax.value_and_grad(pipeline_loss)(
            params, cfg, input_ids, loss_mask, mesh, n_micro=n_micro
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, {"loss": loss}

    return step
