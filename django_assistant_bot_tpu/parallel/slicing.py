"""Device-slice planning: partition the host's devices into disjoint
per-replica submeshes so a fleet's aggregate throughput scales with chips.

Until now every :class:`~..serving.engine.GenerationEngine` replica traced
onto the SAME global mesh in one process: N replicas cost N KV pools on the
same chips, their ticks serialize on the same cores, and aggregate tok/s does
not rise with device count.  The reference's "scale" plane was N stateless
GPU-service pods behind HTTP (PAPER.md §7); the TPU-native equivalent is
replica-per-mesh-slice — :class:`MeshPlanner` cuts ``jax.devices()`` into
``n_devices // replica_devices`` disjoint :class:`DeviceSlice` submeshes,
each with tensor parallelism INSIDE the slice (``model`` is the innermost
mesh axis, so TP collectives ride neighbouring ICI links, exactly as the
global mesh recipe in parallel/mesh.py), and the serving registry pins each
replica's weights, KV page pool, and compiled programs to its own slice
(serving/registry.py; docs/MULTICHIP.md).

Lifecycle contract:

- ``acquire()`` hands out the lowest-numbered free slice; when every slice is
  taken it raises :class:`NoCapacity` — the router's ``add_replica`` (and the
  SLO autoscaler behind it) surface that as an honest "at hardware limit"
  decision instead of cloning another cache onto already-busy chips.
- ``release()`` returns a slice to the pool (replica detach / scale-down);
  releases are idempotent so a detach epilogue racing an engine teardown
  cannot double-free.
- Slices never overlap and never migrate: a replica keeps its slice across
  crash-only restarts (the restarted replica rebuilds ONLY its own slice's
  pool — other slices' warm KV is untouched, tests/test_slicing.py).

CPU recipe (tests, CI, the MULTICHIP dryrun): force a fake 8-device host with
``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (or
``jax.config.update("jax_num_cpu_devices", 8)``) and every slice is a real
submesh with real XLA collectives inside it.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import List, Optional, Sequence

from jax.sharding import Mesh

from .mesh import best_mesh_shape, make_mesh

logger = logging.getLogger(__name__)


class NoCapacity(RuntimeError):
    """Every device slice is already pinned to a replica.

    Carries the planner's shape so the autoscaler / operator surface can say
    "at hardware limit" with numbers instead of a bare failure."""

    def __init__(self, msg: str, *, slices_total: int = 0, replica_devices: int = 0):
        super().__init__(msg)
        self.slices_total = slices_total
        self.replica_devices = replica_devices


@dataclasses.dataclass(frozen=True)
class DeviceSlice:
    """One replica's disjoint share of the host: a slice id, the devices it
    owns, and the submesh built over exactly those devices."""

    slice_id: int
    devices: tuple  # tuple[jax.Device, ...]
    mesh: Mesh

    @property
    def device_ids(self) -> List[int]:
        return [d.id for d in self.devices]


class MeshPlanner:
    """Partition a device list into fixed, disjoint per-replica slices.

    ``replica_devices`` is the topology knob (ModelSpec.replica_devices):
    e.g. 8 devices at ``replica_devices=2`` -> 4 replicas x TP-2.  Within a
    slice the mesh shape follows the global recipe — ``want_model`` defaults
    to the whole slice (pure tensor parallelism, the layout the MULTICHIP
    dryrun exercises at 8B geometry); pass a smaller degree to give the
    remainder to ``data``.

    Thread-safe: ``acquire``/``release`` are called from the registry's boot
    path, the router's scale-up factory (autoscaler thread), and the
    scale-down detach epilogue concurrently.  The lock is a leaf — nothing is
    called out of this class while it is held.
    """

    def __init__(
        self,
        replica_devices: int,
        *,
        devices: Optional[Sequence] = None,
        want_model: int = 0,
        want_seq: int = 1,
        want_expert: int = 1,
    ):
        import jax

        devices = list(devices if devices is not None else jax.devices())
        replica_devices = int(replica_devices)
        if replica_devices < 1:
            raise ValueError(
                f"replica_devices must be >= 1 (got {replica_devices})"
            )
        if replica_devices > len(devices):
            raise ValueError(
                f"replica_devices={replica_devices} exceeds the "
                f"{len(devices)} available device(s)"
            )
        self.replica_devices = replica_devices
        n_slices = len(devices) // replica_devices
        leftover = len(devices) - n_slices * replica_devices
        if leftover:
            # slices are fixed-size and disjoint; a non-dividing knob leaves
            # devices idle — say so loudly, it is almost never intentional
            logger.warning(
                "mesh planner: replica_devices=%d leaves %d of %d device(s) "
                "unused (%d slice(s) planned)",
                replica_devices,
                leftover,
                len(devices),
                n_slices,
            )
        axes = best_mesh_shape(
            replica_devices,
            want_model=want_model or replica_devices,
            want_seq=want_seq,
            want_expert=want_expert,
        )
        self.slice_axes = axes
        self._slices: List[DeviceSlice] = []
        for i in range(n_slices):
            devs = tuple(devices[i * replica_devices : (i + 1) * replica_devices])
            self._slices.append(
                DeviceSlice(
                    slice_id=i,
                    devices=devs,
                    mesh=make_mesh(axes, devices=devs),
                )
            )
        self._lock = threading.Lock()
        self._in_use: set = set()  # slice ids

    @property
    def n_slices(self) -> int:
        return len(self._slices)

    @property
    def slices(self) -> List[DeviceSlice]:
        return list(self._slices)

    def free_slices(self) -> int:
        with self._lock:
            return len(self._slices) - len(self._in_use)

    def acquire(self) -> DeviceSlice:
        """Pin the lowest-numbered free slice; raises :class:`NoCapacity`
        when the host is fully subscribed (the honest scale-up ceiling)."""
        with self._lock:
            for sl in self._slices:
                if sl.slice_id not in self._in_use:
                    self._in_use.add(sl.slice_id)
                    return sl
        raise NoCapacity(
            f"all {len(self._slices)} device slice(s) of "
            f"{self.replica_devices} device(s) are pinned to replicas",
            slices_total=len(self._slices),
            replica_devices=self.replica_devices,
        )

    def release(self, sl: DeviceSlice) -> None:
        """Return a slice to the pool.  Idempotent: a second release of the
        same slice (detach epilogue racing teardown) is a logged no-op."""
        with self._lock:
            if sl.slice_id not in self._in_use:
                logger.warning(
                    "mesh planner: slice %d released twice (ignored)",
                    sl.slice_id,
                )
                return
            self._in_use.discard(sl.slice_id)

    def stats(self) -> dict:
        """JSON-able snapshot for /healthz and /metrics: how many slices
        exist, how many are free, and the per-slice device pinning."""
        with self._lock:
            in_use = set(self._in_use)
        return {
            "replica_devices": self.replica_devices,
            "slices_total": len(self._slices),
            "slices_free": len(self._slices) - len(in_use),
            "slice_axes": {
                "data": self.slice_axes.data,
                "seq": self.slice_axes.seq,
                "model": self.slice_axes.model,
                "expert": self.slice_axes.expert,
                "pipe": self.slice_axes.pipe,
            },
            "slices": [
                {
                    "slice_id": sl.slice_id,
                    "devices": sl.device_ids,
                    "in_use": sl.slice_id in in_use,
                }
                for sl in self._slices
            ],
        }
