"""Logical-axis → PartitionSpec mapping and pytree sharding helpers.

Models annotate every parameter with *logical* axis names (e.g. ``("embed", "mlp")``);
this module maps them to mesh axes and produces :class:`NamedSharding` trees that
``jax.jit``'s ``in_shardings``/``out_shardings`` consume.  This is the scaling-book
recipe: pick a mesh, annotate shardings, let XLA insert the collectives.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from typing import Any, Mapping, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, SEQ_AXIS

_logger = logging.getLogger(__name__)


def _is_quantized(subtree: Any) -> bool:
    """True when a logical-annotation position covers a quantized-weight
    subtree (QTensor / QTensor4) — the only case where :func:`shard_pytree`
    relaxes non-dividing dims to replication instead of failing loudly."""
    from ..ops.quant import QTensor, QTensor4

    return isinstance(subtree, (QTensor, QTensor4))

# Default logical→mesh mapping.  "heads"/"mlp"/"vocab_out" shard over the TP axis;
# "expert" over EP; "batch" over DP; "length" over SP.  Everything else replicates.
DEFAULT_RULES: Mapping[str, Optional[str]] = {
    "batch": DATA_AXIS,
    "length": SEQ_AXIS,
    "heads": MODEL_AXIS,
    "kv_heads": MODEL_AXIS,
    "mlp": MODEL_AXIS,
    "vocab_out": MODEL_AXIS,
    "expert": EXPERT_AXIS,
    "embed": None,
    "head_dim": None,
    "vocab_in": None,
    "pos": None,
}


def compat_shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    Newer jax exposes it at the top level with ``check_vma``; older releases
    (<= 0.4.x) ship ``jax.experimental.shard_map.shard_map`` where the same
    knob is called ``check_rep``.  Callers use the new spelling; this shim
    keeps the package importable (and the 8-device CPU test mesh green) on
    both."""
    try:
        sm = jax.shard_map
    except AttributeError:
        from jax.experimental.shard_map import shard_map as sm_old

        return sm_old(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )
    return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma)


def logical_to_pspec(
    logical_axes: tuple[Optional[str], ...],
    rules: Mapping[str, Optional[str]] = DEFAULT_RULES,
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec."""
    return P(*(rules.get(a) if a is not None else None for a in logical_axes))


def named_sharding(
    mesh: Mesh,
    logical_axes: tuple[Optional[str], ...],
    rules: Mapping[str, Optional[str]] = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_pspec(logical_axes, rules))


def tree_pspecs(logical_tree: Any, rules: Mapping[str, Optional[str]] = DEFAULT_RULES):
    """Map a pytree whose leaves are logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: logical_to_pspec(axes, rules),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def tree_shardings(
    mesh: Mesh, logical_tree: Any, rules: Mapping[str, Optional[str]] = DEFAULT_RULES
):
    return jax.tree.map(
        lambda axes: NamedSharding(mesh, logical_to_pspec(axes, rules)),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def shard_pytree(
    params: Any,
    logical_tree: Any,
    mesh: Mesh,
    rules: Mapping[str, Optional[str]] = DEFAULT_RULES,
):
    """Device-put a parameter pytree according to its logical axis annotations.

    Host→HBM transfer happens once here; afterwards jit-compiled steps consume the
    already-resident sharded arrays (minimising host↔device traffic, the usual HBM
    bottleneck — see SURVEY.md §7 hard parts).

    An annotation position may cover a *subtree* of arrays (e.g. a quantized
    weight is a QTensor of int8 values + per-channel scales); the spec applies
    per leaf, with size-1 dims never sharded — so a scale whose contracted dim
    collapsed to 1 rides the same annotation as its weight.
    """

    def leaf_sharding(axes: tuple, arr, lenient: bool) -> NamedSharding:
        spec = list(logical_to_pspec(axes, rules))
        shape = getattr(arr, "shape", ())
        if len(shape) != len(spec):
            # a silent fallback here would replicate a mis-annotated weight on
            # every device (N-fold HBM) with no diagnostic — fail loudly instead
            raise ValueError(
                f"logical axes {axes} (rank {len(spec)}) do not match array "
                f"shape {tuple(shape)}"
            )
        spec = [None if shape[i] == 1 else s for i, s in enumerate(spec)]
        if lenient:
            # quantized-subtree leaves only: int4-packed weights halve the
            # contraction dim and their grouped scales shrink it to n_groups,
            # either of which can stop dividing a TP axis the full-width
            # weight divided (docs/QUANT.md) — replicate that dim, loudly.
            # Plain weights keep the fail-loudly contract: a silent
            # replicate there would mask a mis-sharded config as N-fold HBM.
            for i, s in enumerate(spec):
                if s is not None and shape[i] % mesh.shape[s] != 0:
                    _logger.warning(
                        "quantized leaf dim %d (size %d) no longer divides "
                        "mesh axis %r (%d): replicating that dim",
                        i,
                        shape[i],
                        s,
                        mesh.shape[s],
                    )
                    spec[i] = None
        return NamedSharding(mesh, P(*spec))

    def subtree_shardings(axes: tuple, subtree):
        lenient = _is_quantized(subtree)
        return jax.tree.map(
            lambda arr: leaf_sharding(axes, arr, lenient), subtree
        )

    shardings = jax.tree.map(
        subtree_shardings,
        logical_tree,
        params,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )

    def put(arr, sharding):
        # Skip no-op re-shardings: device_put of an already-correctly-placed
        # array can still COPY through some backends, and with async dispatch
        # every leaf copies at once — a transient 2x of the whole model's HBM.
        # At 8B geometry that transient (not the model) is what OOM'd a chip
        # with 12 GB free.  Equivalence (not equality) also catches
        # SingleDeviceSharding vs a 1-device mesh NamedSharding.
        cur = getattr(arr, "sharding", None)
        if cur is not None and cur.is_equivalent_to(sharding, getattr(arr, "ndim", 0)):
            return arr
        return jax.device_put(arr, sharding)

    return jax.tree.map(put, params, shardings)


_constraints_off = threading.local()


@contextlib.contextmanager
def constraints_disabled():
    """Suppress :func:`with_constraint` in this thread's dynamic extent.

    Inside a ``shard_map`` body every mesh axis is manual and the body is
    already explicitly partitioned — the logical-axis constraints the model
    code emits are advisory there at best, and older jax rejects them at
    LOWERING time ("axis ... also found in manual_axes"), where the call-site
    try/except below can't reach.  Wrapping the shard_map call keeps the
    primitive out of the trace entirely."""
    prev = getattr(_constraints_off, "depth", 0)
    _constraints_off.depth = prev + 1
    try:
        yield
    finally:
        _constraints_off.depth = prev


def with_constraint(
    x: jax.Array,
    logical_axes: tuple[Optional[str], ...],
    rules: Mapping[str, Optional[str]] = DEFAULT_RULES,
) -> jax.Array:
    """`with_sharding_constraint` by logical axis names (no-op outside jit/mesh)."""
    if getattr(_constraints_off, "depth", 0):
        return x
    try:
        return jax.lax.with_sharding_constraint(x, logical_to_pspec(logical_axes, rules))
    except (ValueError, RuntimeError):
        return x
