"""Example app entry: framework CLI with the example BOTS registry applied."""

from __future__ import annotations

import sys

from django_assistant_bot_tpu.cli.main import main

from .settings import configure

if __name__ == "__main__":
    configure()
    sys.exit(main())
