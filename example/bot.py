"""Example app: TaskManagerBot (reference: example/bot/bot.py:17-359).

Demonstrates the framework's extension surface: intent classification with the
fast model, a state-machine task-creation flow checkpointed in ``Instance.state``,
regex command decorators, inline keyboards, and MultiPartAnswer.  Tasks live in
the instance state (the reference does the same — no extra tables).
"""

from __future__ import annotations

import re
from typing import Optional

from django_assistant_bot_tpu.ai.providers.base import AIDebugger
from django_assistant_bot_tpu.bot.assistant_bot import AssistantBot
from django_assistant_bot_tpu.bot.domain import (
    Answer,
    Button,
    MultiPartAnswer,
    SingleAnswer,
)
from django_assistant_bot_tpu.bot.services.context_service.utils import add_system_message
from django_assistant_bot_tpu.utils.repeat_until import repeat_until

INTENTS = ("#create_task", "#list_tasks", "#other")


class TaskManagerBot(AssistantBot):
    DEFAULT_LANGUAGE = "en"

    async def get_answer_to_messages(self, messages, debug_info, do_interrupt) -> Answer:
        if self.instance.state.get("awaiting_input"):
            return await self.handle_state_input(messages, debug_info)
        category = await self._classify_intent(messages, debug_info)
        if category == "#create_task":
            return await self.initiate_task_creation()
        if category == "#list_tasks":
            return await self.show_task_list()
        return await self.handle_general_query(messages, debug_info)

    # ------------------------------------------------------- intent detection
    async def _classify_intent(self, messages, debug_info) -> str:
        with AIDebugger(self._fast_ai, debug_info, "intent_classification") as dbg:
            system_msg = (
                "Classify the user request above:\n"
                "#create_task - creating a new task\n"
                "#list_tasks - request task list\n"
                "#other - other requests"
            )
            response = await repeat_until(
                dbg.ai.get_response,
                add_system_message(messages, system_msg),
                condition=lambda r: any(i in r.result for i in INTENTS),
                max_attempts=5,
            )
            intent = next((i for i in INTENTS if i in response.result), "#other")
            dbg.node["detected_intent"] = intent
            return intent

    # ------------------------------------------------------ creation workflow
    async def initiate_task_creation(self) -> SingleAnswer:
        await self.update_state({"awaiting_input": "task_title", "new_task": {}})
        return SingleAnswer(
            "📝 Enter task name:",
            buttons=[[Button("Cancel", callback_data="/cancel")]],
        )

    async def handle_state_input(self, messages, debug_info) -> Answer:
        awaiting = self.instance.state.get("awaiting_input")
        text = messages[-1]["content"] if messages else ""
        if awaiting == "task_title":
            new_task = dict(self.instance.state.get("new_task") or {})
            new_task["title"] = text.strip()
            await self.update_state({"awaiting_input": "priority", "new_task": new_task})
            return SingleAnswer(
                f"Priority for *{new_task['title']}*?",
                buttons=[
                    [Button(p.title(), callback_data=f"/priority {p}")]
                    for p in ("high", "medium", "low")
                ],
            )
        return SingleAnswer("Please use the buttons above.", no_store=True)

    @AssistantBot.command(r"/priority (high|medium|low)")
    async def set_priority(self, match: re.Match, message_id: Optional[int] = None):
        new_task = dict(self.instance.state.get("new_task") or {})
        new_task["priority"] = match.group(1)
        await self.update_state({"awaiting_input": "confirm", "new_task": new_task})
        return await self._confirm_task_creation()

    async def _confirm_task_creation(self) -> SingleAnswer:
        new_task = self.instance.state.get("new_task") or {}
        return SingleAnswer(
            (
                "Confirm task creation:\n"
                f"*Title:* {new_task.get('title')}\n"
                f"*Priority:* {new_task.get('priority')}"
            ),
            buttons=[
                [
                    Button("✅ Confirm", callback_data="/confirm_task"),
                    Button("❌ Cancel", callback_data="/cancel"),
                ]
            ],
        )

    @AssistantBot.command(r"/confirm_task")
    async def finalize_task(self, match=None, message_id: Optional[int] = None):
        new_task = self.instance.state.get("new_task") or {}
        if not new_task.get("title"):
            return SingleAnswer("Nothing to confirm.", no_store=True)
        tasks = list(self.instance.state.get("tasks") or [])
        tasks.append({"title": new_task["title"], "priority": new_task.get("priority", "medium")})
        await self.update_state({"tasks": tasks, "awaiting_input": None, "new_task": {}})
        return MultiPartAnswer(
            parts=[
                SingleAnswer(f"✅ Task *{new_task['title']}* created."),
                SingleAnswer(f"You now have {len(tasks)} task(s). Use /list to view them."),
            ],
            no_store=True,
        )

    @AssistantBot.command(r"/cancel")
    async def cancel_operation(self, match=None, message_id: Optional[int] = None):
        await self.update_state({"awaiting_input": None, "new_task": {}})
        return SingleAnswer("Operation cancelled.", no_store=True)

    # ------------------------------------------------------------------ lists
    @AssistantBot.command(r"/list")
    async def command_list(self, match=None, message_id: Optional[int] = None):
        return await self.show_task_list()

    async def show_task_list(self) -> SingleAnswer:
        tasks = self.instance.state.get("tasks") or []
        if not tasks:
            return SingleAnswer("No tasks yet. Send /new_task to create one.", no_store=True)
        marks = {"high": "🔴", "medium": "🟡", "low": "🟢"}
        lines = [
            f"{marks.get(t.get('priority'), '•')} {i + 1}. {t['title']}"
            for i, t in enumerate(tasks)
        ]
        return SingleAnswer("*Your tasks:*\n" + "\n".join(lines), no_store=True)

    @AssistantBot.command(r"/new_task")
    async def command_new_task(self, match=None, message_id: Optional[int] = None):
        return await self.initiate_task_creation()

    # ------------------------------------------------------------------ misc
    async def handle_general_query(self, messages, debug_info) -> Optional[Answer]:
        return await super().get_answer_to_messages(messages, debug_info, None)

    async def command_start(self, text: str):
        return SingleAnswer(
            "👋 I'm the task manager bot.\n"
            "Send /new_task to create a task, /list to see your tasks.",
            no_store=True,
        )

    async def command_help(self):
        return SingleAnswer(
            "/new_task — create a task\n/list — show tasks\n/cancel — abort",
            no_store=True,
        )
