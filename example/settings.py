"""Example deployment config (reference: example/example/settings.py:55-70).

Run with::

    python -m example.run chat taskmanager
"""

from __future__ import annotations

from django_assistant_bot_tpu.conf import settings

BOTS = {
    "taskmanager": {
        "class": "example.bot.TaskManagerBot",
        "telegram_token": None,  # set via DABT_TELEGRAM_TOKEN or Bot row
    }
}


def configure() -> None:
    settings.BOTS = BOTS
