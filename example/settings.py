"""Example deployment config (reference: example/example/settings.py:55-70).

Run with::

    python -m example.run chat taskmanager
"""

from __future__ import annotations

import os

from django_assistant_bot_tpu.conf import settings

BOTS = {
    "taskmanager": {
        "class": "example.bot.TaskManagerBot",
        "telegram_token": None,  # set via DABT_TELEGRAM_TOKEN or Bot row
    }
}

# Per-bot file resources (prompts/, messages/<lang>/, phrases/<lang>.json) —
# the reference ships example/bot/resources/task_manager/phrases/ru.json
RESOURCES_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "resources")


def configure() -> None:
    settings.BOTS = BOTS
    if not settings.RESOURCES_DIR:
        settings.RESOURCES_DIR = RESOURCES_DIR
