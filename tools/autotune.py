#!/usr/bin/env python3
"""Standalone wrapper for the byte-ledger decode geometry autotuner.

Usage (pure arithmetic — no jax, no weights, runs anywhere):

    python tools/autotune.py --layers 32 --hidden 4096 --intermediate 14336 \
        --heads 32 --kv-heads 8 --head-dim 128 --vocab 128256 \
        --max-seq-len 8192 --weight-bits 4 --hbm-budget-gb 16

Prints the recommended {kv_page_size, max_slots, decode_steps} plus the
modeled tok/s ranking and the assumptions behind it.  The in-server variant
is ``dabt serve --autotune`` (reads geometry from the model config); the
model itself lives in django_assistant_bot_tpu/serving/autotune.py and is
documented in docs/QUANT.md.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
)

from django_assistant_bot_tpu.serving.autotune import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main())
