"""Finding model and checker registry for dabtlint.

Every checker has a stable code, a one-line description, and a fix-it hint.
A finding's *identity* — the key the baseline matches on — deliberately
excludes line numbers: ``(code, module, symbol, detail)``.  Unrelated edits
above a baselined site must not resurrect it, and a baselined site that moves
within its function stays baselined.  The ``detail`` string is therefore
written by checkers from stable names (lock classes, callee names, hot-path
roots), never from positions.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

CHECKERS: Dict[str, Dict[str, str]] = {
    "DABT101": {
        "title": "lock-order cycle",
        "description": (
            "The static lock-acquisition graph (with-blocks, acquire() spans, "
            "calls made inside a span, and Future-resolution -> done-callback "
            "edges) contains a cycle: two threads taking these locks in "
            "opposite orders can deadlock (the PR 7 router/scheduler ABBA "
            "shape)."
        ),
        "hint": (
            "Break the cycle: resolve futures and run callbacks OUTSIDE the "
            "lock (collect under the lock, act after releasing), or impose a "
            "single global acquisition order."
        ),
    },
    "DABT102": {
        "title": "future resolved while holding a lock",
        "description": (
            "set_result/set_exception/cancel (or a project helper that calls "
            "them) runs while a lock is held.  Done-callbacks run "
            "synchronously on the resolving thread and may take other locks "
            "— the raw material of every ABBA deadlock this repo has shipped."
        ),
        "hint": (
            "Collect the futures under the lock, release it, then resolve "
            "(see RequestScheduler.drain for the pattern)."
        ),
    },
    "DABT103": {
        "title": "blocking call in async def",
        "description": (
            "A blocking call (time.sleep, sync HTTP, subprocess, an "
            "un-timed-out acquire) inside an async function stalls the whole "
            "event loop — every SSE stream and health probe on it."
        ),
        "hint": (
            "Use the async equivalent (asyncio.sleep, aiohttp), offload via "
            "asyncio.to_thread, or pass a timeout to acquire()."
        ),
    },
    "DABT104": {
        "title": "device->host sync reachable from a hot path",
        "description": (
            "A host-synchronizing call (.item()/.tolist()/np.asarray/"
            "jax.device_get/block_until_ready, or float()/int() of a traced "
            "value) is reachable from the decode hot-path registry "
            "(_process_tick / decode_step* / spec tick / paged ops).  Each "
            "one stalls the dispatch pipeline for a device round trip."
        ),
        "hint": (
            "Keep device values on device through the tick; batch host reads "
            "through the existing async copy path, or move the sync off the "
            "hot path."
        ),
    },
    "DABT105": {
        "title": "non-injectable time in a clock-disciplined module",
        "description": (
            "Raw time.time()/time.monotonic()/time.sleep() in a serving "
            "module that already follows the injectable clock=/sleep= "
            "convention.  Raw sites are invisible to fake-clock tests — the "
            "chaos/drain suites depend on every timestamp being injectable."
        ),
        "hint": (
            "Thread the module's clock()/sleep() parameters through (default "
            "them to time.monotonic/time.sleep so behavior is unchanged)."
        ),
    },
}


@dataclasses.dataclass(frozen=True)
class Finding:
    code: str
    module: str  # repo-relative path, '/'-separated
    symbol: str  # function/method qualname ('<module>' for module level)
    detail: str  # stable, line-free description (baseline identity)
    line: int  # 1-based; display only, never part of the identity
    col: int = 0

    @property
    def key(self) -> Tuple[str, str, str, str]:
        return (self.code, self.module, self.symbol, self.detail)

    @property
    def hint(self) -> str:
        return CHECKERS[self.code]["hint"]

    def render(self, show_hint: bool = True) -> str:
        head = f"{self.module}:{self.line}: {self.code} [{self.symbol}] {self.detail}"
        if show_hint:
            return f"{head}\n    fix: {self.hint}"
        return head


def parse_code_list(text: str) -> Optional[set]:
    """'DABT101,DABT105' -> {'DABT101', 'DABT105'}; '' / 'all' -> None (all)."""
    text = (text or "").strip()
    if not text or text.lower() == "all":
        return None
    codes = {c.strip().upper() for c in text.split(",") if c.strip()}
    unknown = codes - set(CHECKERS)
    if unknown:
        raise ValueError(f"unknown checker code(s): {sorted(unknown)}")
    return codes
