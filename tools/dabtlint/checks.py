"""The five dabtlint checkers over the project event model.

Interprocedural core: per-function summaries computed to a fixpoint over the
project call graph —

- ``acquires*(f)``  every lock class ``f`` may acquire, directly or through
  any resolvable call chain
- ``resolves*(f)``  whether ``f`` may resolve a Future (set_result /
  set_exception / cancel / a helper like ``_safe_resolve``), and via whom

DABT101 builds the global lock-acquisition-order graph from three edge
sources: direct nested acquisition, calls made while holding a lock (edges to
everything the callee may acquire), and Future-resolution sites while holding
a lock (edges to everything any registered done-callback may acquire — the
exact shape of both PR 7 deadlocks, where ``Future.set_result`` under lock A
ran a router callback that took lock B).  A cycle in that graph is a
deadlock two threads can reach by interleaving.
"""

from __future__ import annotations

import ast
import fnmatch
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .findings import Finding
from .locks import FunctionEvents, _acquire_is_timed, _expr_display, extract_events
from .project import FunctionInfo, Project

# Functions whose call trees are decode-tick hot paths: a device->host sync
# anywhere under these stalls the pipelined tick (DABT104).  Matched with
# fnmatch against both the bare qualname and "module.py::qualname".
HOT_PATH_PATTERNS: Tuple[str, ...] = (
    "*._process_tick",
    "_process_tick",
    "*._issue_tick",
    "decode_step*",
    "*.decode_step*",
    "*spec_tick*",
    "verify_tree_step*",
    "commit_tree_path*",
    "*paged_gqa_decode_attention",
    "paged_tree_attention",
    "insert_sequences_paged",
    "prefill_suffix_paged",
    "prefill_chunk_paged",
    # fused multi-step decode tick (decode_steps > 1): the N-step scan body
    # and its builder — a host sync inside would stall ALL N steps of every
    # tick, so the builder closure tree is a root in its own right
    "*._make_decode_tick*",
    # double-buffered host->device uploads: runs between ticks while device
    # work is in flight; a sync here would serialize the overlap away
    "*._upload_dirty",
    "*._prestage_uploads",
    "*._refresh_sampling",
    # quantized in-dot dequant (int8 per-channel / int4 grouped): the weight
    # read path of every decode/prefill/verify dot
    "qeinsum",
    "*.qeinsum",
    "unpack_int4",
    # observability recorder entry points (serving/obs.py): called from the
    # tick path's host bookkeeping, so metric recording can never silently
    # add a device sync — roots in their own right, independent of whether
    # the engine's `self.obs` attribute type resolves
    "*EngineObs.on_tick",
    "*EngineObs.on_spec_tick",
    "*EngineObs.on_first_token",
    "*EngineObs.on_token_gap",
    "*Histogram.observe",
    "*FlightRecorder.record",
)

# Modules under these path segments are clock-disciplined candidates for
# DABT105 (the serving plane's injectable-clock convention).
CLOCK_DISCIPLINE_DIRS: Tuple[str, ...] = ("serving",)

HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
# canonical module.attr forms; call sites are canonicalized through the
# module's import table first, so `import numpy as _np; _np.asarray(x)`
# resolves to numpy.asarray and cannot dodge the checker via an alias
HOST_SYNC_CALLS = {
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}
BLOCKING_HTTP_PREFIXES = ("requests.", "urllib.request.", "http.client.")
RAW_TIME_CALLS = {"time.time", "time.monotonic", "time.sleep"}


def _dedupe(findings: Iterable[Finding]) -> List[Finding]:
    # collapse per (identity, line) — identical keys at DIFFERENT lines stay
    # separate findings (each site can be suppressed on its own line; one
    # baseline entry still accepts all of them, by design)
    seen: Dict[Tuple, Finding] = {}
    for f in findings:
        seen.setdefault((f.key, f.line), f)
    return sorted(seen.values(), key=lambda f: (f.module, f.line, f.code, f.detail))


def _short_lock(lock: str) -> str:
    """'pkg/serving/scheduler.py::RequestScheduler._lock' ->
    'RequestScheduler._lock' (display/detail form: file-move stable)."""
    return lock.rsplit("::", 1)[-1]


class Analysis:
    def __init__(self, project: Project):
        self.project = project
        self.events: Dict[str, FunctionEvents] = extract_events(project)
        self._by_fi: Dict[int, FunctionEvents] = {
            id(ev.fi): ev for ev in self.events.values()
        }
        self.acquires_trans: Dict[str, Set[str]] = {}
        self.resolves_trans: Dict[str, Optional[str]] = {}
        self.callbacks: List[FunctionInfo] = []
        self._summarize()

    # ------------------------------------------------------------- summaries
    def _summarize(self) -> None:
        acq: Dict[str, Set[str]] = {}
        res: Dict[str, Optional[str]] = {}
        for disp, ev in self.events.items():
            acq[disp] = {a.lock for a in ev.acquires}
            res[disp] = "directly" if ev.resolves else None
        changed = True
        while changed:
            changed = False
            for disp, ev in self.events.items():
                for call in ev.calls:
                    for g in call.targets:
                        gdisp = g.display
                        extra = acq.get(gdisp, set()) - acq[disp]
                        if extra:
                            acq[disp] |= extra
                            changed = True
                        if res[disp] is None and res.get(gdisp) is not None:
                            res[disp] = f"via {g.qualname}()"
                            changed = True
        self.acquires_trans = acq
        self.resolves_trans = res
        cb_seen: Set[int] = set()
        for ev in self.events.values():
            for reg in ev.registers:
                for t in reg.targets:
                    if id(t) not in cb_seen:
                        cb_seen.add(id(t))
                        self.callbacks.append(t)

    def _resolution_sites(self, ev: FunctionEvents) -> List[Tuple[int, Tuple[str, ...], str]]:
        """(line, held, how) for every point in ``ev.fi`` where a Future may
        resolve while at least one lock is held."""
        out: List[Tuple[int, Tuple[str, ...], str]] = []
        for r in ev.resolves:
            if r.held:
                out.append((r.line, r.held, f"{r.receiver}.{r.method}()"))
        for call in ev.calls:
            if not call.held:
                continue
            for g in call.targets:
                how = self.resolves_trans.get(g.display)
                if how is not None:
                    out.append(
                        (call.line, call.held, f"call to {g.qualname}() ({how})")
                    )
        return out

    # --------------------------------------------------------------- DABT101
    def check_lock_order(self) -> List[Finding]:
        edges: Dict[Tuple[str, str], Tuple[FunctionEvents, int, str]] = {}

        def add(a: str, b: str, ev: FunctionEvents, line: int, via: str) -> None:
            if a != b and (a, b) not in edges:
                edges[(a, b)] = (ev, line, via)

        for ev in self.events.values():
            for acqev in ev.acquires:
                for h in acqev.held:
                    add(h, acqev.lock, ev, acqev.line, "nested acquisition")
            for call in ev.calls:
                if not call.held:
                    continue
                for g in call.targets:
                    for lock in self.acquires_trans.get(g.display, ()):
                        for h in call.held:
                            add(h, lock, ev, call.line, f"call to {g.qualname}()")
            for line, held, how in self._resolution_sites(ev):
                for cb in self.callbacks:
                    for lock in self.acquires_trans.get(cb.display, ()):
                        for h in held:
                            add(
                                h,
                                lock,
                                ev,
                                line,
                                f"{how} -> done-callback {cb.qualname}()",
                            )
        return self._cycles(edges)

    def _cycles(
        self, edges: Dict[Tuple[str, str], Tuple[FunctionEvents, int, str]]
    ) -> List[Finding]:
        graph: Dict[str, Set[str]] = {}
        for a, b in edges:
            graph.setdefault(a, set()).add(b)
            graph.setdefault(b, set())
        sccs = _tarjan(graph)
        findings = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            cyc = _one_cycle(graph, scc)
            if not cyc:
                continue
            # canonical rotation: start at the smallest lock id
            start = cyc.index(min(cyc))
            cyc = cyc[start:] + cyc[:start]
            display = " -> ".join(_short_lock(c) for c in cyc + [cyc[0]])
            legs = []
            first_site = None
            for a, b in zip(cyc, cyc[1:] + [cyc[0]]):
                ev, line, via = edges[(a, b)]
                legs.append(
                    f"{_short_lock(a)} -> {_short_lock(b)} "
                    f"[{ev.fi.module.relpath}:{line} {ev.fi.qualname}, {via}]"
                )
                if first_site is None:
                    first_site = (ev, line)
            ev, line = first_site
            findings.append(
                Finding(
                    "DABT101",
                    ev.fi.module.relpath,
                    ev.fi.qualname,
                    f"lock-order cycle {display}; legs: " + "; ".join(legs),
                    line,
                )
            )
        return _dedupe(findings)

    # --------------------------------------------------------------- DABT102
    def check_future_under_lock(self) -> List[Finding]:
        findings = []
        for ev in self.events.values():
            for line, held, how in self._resolution_sites(ev):
                held_disp = ", ".join(sorted(_short_lock(h) for h in held))
                findings.append(
                    Finding(
                        "DABT102",
                        ev.fi.module.relpath,
                        ev.fi.qualname,
                        f"{how} while holding {held_disp}",
                        line,
                    )
                )
        return _dedupe(findings)

    # --------------------------------------------------------------- DABT103
    def check_async_blocking(self) -> List[Finding]:
        findings = []
        for ev in self.events.values():
            fi = ev.fi
            if not fi.is_async:
                continue
            for call, display, awaited in _async_body_calls(fi.node):
                if awaited:
                    continue
                desc = None
                if display in RAW_TIME_CALLS and display.endswith("sleep"):
                    desc = "time.sleep() blocks the event loop"
                elif (
                    display == "sleep"
                    and fi.module.imports.get("sleep") == "time.sleep"
                ):
                    desc = "time.sleep() blocks the event loop"
                elif display.startswith("subprocess.") or display == "os.system":
                    desc = f"{display}() runs a blocking subprocess"
                elif display.startswith(BLOCKING_HTTP_PREFIXES):
                    desc = f"{display}() is synchronous HTTP"
                elif display.endswith(".acquire") or display == "acquire":
                    if not _acquire_is_timed(call):
                        desc = f"{display}() without a timeout can block forever"
                if desc is not None:
                    findings.append(
                        Finding(
                            "DABT103",
                            fi.module.relpath,
                            fi.qualname,
                            f"{desc} inside async def",
                            call.lineno,
                        )
                    )
        return _dedupe(findings)

    # --------------------------------------------------------------- DABT104
    def check_hot_path_syncs(self) -> List[Finding]:
        roots: Dict[str, str] = {}  # display -> root qualname
        order: List[str] = []
        for disp, ev in self.events.items():
            q = ev.fi.qualname
            if any(
                fnmatch.fnmatch(q, pat) or fnmatch.fnmatch(disp, pat)
                for pat in HOT_PATH_PATTERNS
            ):
                roots[disp] = q
                order.append(disp)
        reach: Dict[str, str] = {}
        for root in sorted(order):
            stack = [root]
            while stack:
                disp = stack.pop()
                if disp in reach:
                    continue
                reach[disp] = roots[root]
                ev = self.events.get(disp)
                if ev is None:
                    continue
                for call in ev.calls:
                    for g in call.targets:
                        if g.display not in reach:
                            stack.append(g.display)
        findings = []
        for disp, root in reach.items():
            ev = self.events.get(disp)
            if ev is None:
                continue
            for desc, line in _host_sync_sites(ev.fi):
                findings.append(
                    Finding(
                        "DABT104",
                        ev.fi.module.relpath,
                        ev.fi.qualname,
                        f"{desc} reachable from hot path {root}",
                        line,
                    )
                )
        return _dedupe(findings)

    # --------------------------------------------------------------- DABT105
    def check_raw_time(self) -> List[Finding]:
        findings = []
        for m in self.project.modules:
            parts = m.relpath.split("/")
            if not any(d in parts for d in CLOCK_DISCIPLINE_DIRS):
                continue
            if not _module_has_clock_convention(m):
                continue
            for fi in m.functions.values():
                for node in _walk_own_body(fi.node):
                    if not isinstance(node, ast.Call):
                        continue
                    display = _expr_display(node.func)
                    bare = m.imports.get(display, "")
                    if display in RAW_TIME_CALLS or bare in RAW_TIME_CALLS:
                        name = display if display in RAW_TIME_CALLS else bare
                        findings.append(
                            Finding(
                                "DABT105",
                                m.relpath,
                                fi.qualname,
                                f"raw {name}() in a clock-disciplined module",
                                node.lineno,
                            )
                        )
        return _dedupe(findings)

    # ------------------------------------------------------------------- all
    def run(self, select: Optional[Set[str]] = None) -> List[Finding]:
        checks = {
            "DABT101": self.check_lock_order,
            "DABT102": self.check_future_under_lock,
            "DABT103": self.check_async_blocking,
            "DABT104": self.check_hot_path_syncs,
            "DABT105": self.check_raw_time,
        }
        out: List[Finding] = []
        for code, fn in checks.items():
            if select is None or code in select:
                out.extend(fn())
        return sorted(out, key=lambda f: (f.module, f.line, f.code, f.detail))


def run_analysis(
    roots: Sequence[str],
    *,
    base_dir: Optional[str] = None,
    select: Optional[Set[str]] = None,
) -> List[Finding]:
    project = Project.load(roots, base_dir=base_dir)
    return Analysis(project).run(select)


# ----------------------------------------------------------------- helpers
def _walk_own_body(fnode: ast.AST):
    """Walk a function's OWN body, skipping nested function/lambda subtrees —
    those are enumerated as their own FunctionInfos (or deferred payloads),
    and walking them here would double-report every site inside them."""
    stack = list(ast.iter_child_nodes(fnode))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _module_has_clock_convention(m) -> bool:
    """The module opted into injectable time: some function takes a ``clock``
    or ``sleep`` parameter, or some class carries self._clock/self._sleep."""
    for fi in m.functions.values():
        args = fi.node.args
        for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
            if a.arg in ("clock", "sleep"):
                return True
    for node in ast.walk(m.tree):
        if (
            isinstance(node, ast.Attribute)
            and node.attr in ("_clock", "_sleep")
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return True
    return False


def _async_body_calls(node: ast.AST):
    """(call, display, awaited) for the async function's own body, skipping
    nested function/lambda bodies (they run elsewhere)."""
    awaited_ids = set()
    stack = list(ast.iter_child_nodes(node))
    flat = []
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Await) and isinstance(n.value, ast.Call):
            awaited_ids.add(id(n.value))
        if isinstance(n, ast.Call):
            flat.append(n)
        stack.extend(ast.iter_child_nodes(n))
    for call in flat:
        yield call, _expr_display(call.func), id(call) in awaited_ids


def _host_sync_sites(fi: FunctionInfo):
    """(description, line) for device->host syncs in one function, with a
    local taint pass so float()/int() only fire on values that flowed from a
    jnp/jax expression in the same function."""
    tainted: Set[str] = set()

    def expr_tainted(e: ast.AST) -> bool:
        for sub in ast.walk(e):
            if isinstance(sub, ast.Name) and (
                sub.id in tainted or sub.id in ("jnp", "jax")
            ):
                return True
        return False

    # forward taint pass in statement order (_walk_own_body is close enough:
    # the function bodies we care about assign before use)
    for stmt in _walk_own_body(fi.node):
        if isinstance(stmt, ast.Assign) and expr_tainted(stmt.value):
            for tgt in stmt.targets:
                for sub in ast.walk(tgt):
                    if isinstance(sub, ast.Name):
                        tainted.add(sub.id)
    imports = fi.module.imports
    for node in _walk_own_body(fi.node):
        if not isinstance(node, ast.Call):
            continue
        display = _expr_display(node.func)
        # canonicalize the root through the import table, so aliased imports
        # (`import numpy as _np`) cannot dodge the checker
        root, dot, rest = display.partition(".")
        canonical = f"{imports.get(root, root)}{dot}{rest}"
        if isinstance(node.func, ast.Attribute) and node.func.attr in HOST_SYNC_METHODS:
            yield f"{display}() forces a device->host sync", node.lineno
        elif canonical in HOST_SYNC_CALLS:
            yield f"{display}() copies device memory to host", node.lineno
        elif (
            display in ("float", "int")
            and len(node.args) == 1
            and expr_tainted(node.args[0])
        ):
            yield (
                f"{display}() of a traced/device value forces a host sync",
                node.lineno,
            )


def _tarjan(graph: Dict[str, Set[str]]) -> List[List[str]]:
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        # iterative Tarjan (engine-sized call graphs overflow recursion)
        work = [(v, iter(sorted(graph.get(v, ()))))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(graph.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                out.append(scc)

    for v in sorted(graph):
        if v not in index:
            strongconnect(v)
    return out


def _one_cycle(graph: Dict[str, Set[str]], scc: List[str]) -> List[str]:
    """One simple cycle inside an SCC, for display."""
    members = set(scc)
    start = min(scc)
    path = [start]
    seen = {start}
    node = start
    while True:
        nxt = None
        for w in sorted(graph.get(node, ())):
            if w == start and len(path) > 1:
                return path
            if w in members and w not in seen:
                nxt = w
                break
        if nxt is None:
            # backtrack-free walk failed (rare); fall back to any 2-cycle
            for a in sorted(members):
                for b in sorted(graph.get(a, ())):
                    if b in members and a in graph.get(b, set()):
                        return [a, b]
            return []
        path.append(nxt)
        seen.add(nxt)
        node = nxt
