"""Lock identities, acquisition spans, and per-function event extraction.

A *lock identity* is a class of locks, not an instance — every
``RequestScheduler`` shares the node ``serving/scheduler.py::RequestScheduler._lock``
exactly like FreeBSD WITNESS merges lock instances into lock classes.  The
acquisition-order graph and the cycle check run over these classes.

Per function, one walk produces an ordered event list:

- ``AcquireEvent``  — a ``with lock:`` entry or a bare ``lock.acquire()``,
  with the set of lock classes already held at that point (a bare acquire
  holds until the matching ``release()`` in the same statement list, else to
  the end of the function — a deliberate over-approximation: spans that leak
  are a finding-shaped smell on their own)
- ``CallEvent``     — a resolved project call with the held-set at the site
- ``ResolveEvent``  — a direct future resolution (``set_result`` /
  ``set_exception`` / ``_resolve``, plus ``cancel`` on a future-named
  receiver) with the held-set
- ``RegisterEvent`` — an ``add_done_callback(cb)`` registration; ``cb`` is
  resolved to project functions (lambdas contribute the calls in their body)

Alias resolution covers the shapes this repo actually writes: ``self._lock``,
module-level ``_lock``, a local ``lk = self._lock`` rebinding, and locks
created locally in the function.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from .project import FunctionInfo, ModuleInfo, Project, _is_lock_factory_call

RESOLVE_METHODS = {"set_result", "set_exception", "_resolve"}
FUTURE_NAME_HINTS = ("fut", "future", "promise")


@dataclasses.dataclass
class AcquireEvent:
    lock: str
    held: Tuple[str, ...]
    line: int
    blocking_noarg: bool = False  # bare .acquire() with no timeout


@dataclasses.dataclass
class CallEvent:
    node: ast.Call
    targets: List[FunctionInfo]
    held: Tuple[str, ...]
    line: int
    display: str


@dataclasses.dataclass
class ResolveEvent:
    method: str
    receiver: str
    held: Tuple[str, ...]
    line: int


@dataclasses.dataclass
class RegisterEvent:
    targets: List[FunctionInfo]
    line: int


@dataclasses.dataclass
class FunctionEvents:
    fi: FunctionInfo
    acquires: List[AcquireEvent]
    calls: List[CallEvent]
    resolves: List[ResolveEvent]
    registers: List[RegisterEvent]


def _expr_display(expr: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
    return ".".join(reversed(parts)) or "<expr>"


def _call_display(call: ast.Call) -> str:
    return _expr_display(call.func) if not isinstance(call.func, ast.Call) else "<call>"


def _walk_no_lambda(node: ast.AST):
    """ast.walk that does not descend into Lambda bodies (deferred code) —
    a call inside ``add_done_callback(lambda f: ...)`` runs at resolution
    time, not at the registration site."""
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, ast.Lambda):
                continue
            stack.append(child)


def _looks_like_future(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in FUTURE_NAME_HINTS)


def _acquire_is_timed(call: ast.Call) -> bool:
    """True when an ``.acquire(...)`` call cannot block forever: it carries a
    timeout (kwarg or 2nd positional), or it is the non-blocking try-acquire
    form (``acquire(False)`` / ``acquire(blocking=False)``)."""
    if any(kw.arg == "timeout" for kw in call.keywords) or len(call.args) >= 2:
        return True
    if call.args and isinstance(call.args[0], ast.Constant) and not call.args[0].value:
        return True  # acquire(False) / acquire(0): try-acquire, never blocks
    return any(
        kw.arg == "blocking"
        and isinstance(kw.value, ast.Constant)
        and not kw.value.value
        for kw in call.keywords
    )


class LockResolver:
    """Maps lock-shaped expressions to lock-class identities."""

    def __init__(self, project: Project):
        self.project = project

    def lock_id(
        self,
        fi: FunctionInfo,
        expr: ast.AST,
        aliases: Dict[str, str],
        local_locks: Dict[str, str],
    ) -> Optional[str]:
        m = fi.module
        if isinstance(expr, ast.Name):
            if expr.id in aliases:
                return aliases[expr.id]
            if expr.id in local_locks:
                return local_locks[expr.id]
            if expr.id in m.module_locks:
                return f"{m.relpath}::{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            base = expr.value
            if isinstance(base, ast.Name):
                if base.id == "self" and fi.cls is not None:
                    owner = self._class_owning_lock(m, fi.cls, expr.attr)
                    if owner is not None:
                        omod, ocls = owner
                        return f"{omod.relpath}::{ocls}.{expr.attr}"
                    return None
                tm = self.project.resolve_module(m, base.id)
                if tm is not None and expr.attr in tm.module_locks:
                    return f"{tm.relpath}::{expr.attr}"
                return None
            # self.attr._lock — a known-typed attribute's lock
            if (
                isinstance(base, ast.Attribute)
                and isinstance(base.value, ast.Name)
                and base.value.id == "self"
                and fi.cls is not None
            ):
                ci = m.classes.get(fi.cls)
                if ci is not None and base.attr in ci.attr_types:
                    cmod, cname = ci.attr_types[base.attr]
                    owner = self._class_owning_lock(cmod, cname, expr.attr)
                    if owner is not None:
                        omod, ocls = owner
                        return f"{omod.relpath}::{ocls}.{expr.attr}"
        return None

    def _class_owning_lock(
        self, mod: ModuleInfo, cls_name: str, attr: str, _seen=None
    ) -> Optional[Tuple[ModuleInfo, str]]:
        _seen = _seen or set()
        if (id(mod), cls_name) in _seen:
            return None
        _seen.add((id(mod), cls_name))
        ci = mod.classes.get(cls_name)
        if ci is None:
            return None
        if attr in ci.lock_attrs:
            return (mod, cls_name)
        for base in ci.bases:
            resolved = self.project.resolve_class_by_name(mod, base)
            if resolved is not None:
                owner = self._class_owning_lock(resolved[0], resolved[1], attr, _seen)
                if owner is not None:
                    return owner
        return None


class _FunctionWalker:
    """One pass over a function body tracking the held lock-class stack."""

    def __init__(self, project: Project, resolver: LockResolver, fi: FunctionInfo):
        self.project = project
        self.resolver = resolver
        self.fi = fi
        self.aliases: Dict[str, str] = {}
        self.local_locks: Dict[str, str] = {}
        self.local_types = project._local_var_types(fi)
        self.held: List[str] = []
        self.out = FunctionEvents(fi, [], [], [], [])

    # -- helpers -----------------------------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        return self.resolver.lock_id(self.fi, expr, self.aliases, self.local_locks)

    def _push(self, lock: str, line: int, *, blocking_noarg: bool = False) -> bool:
        if lock in self.held:
            return False  # re-entrant view: one class node per thread stack
        self.out.acquires.append(
            AcquireEvent(lock, tuple(self.held), line, blocking_noarg)
        )
        self.held.append(lock)
        return True

    def _pop(self, lock: str) -> None:
        if lock in self.held:
            self.held.remove(lock)

    # -- statement walk ----------------------------------------------------
    def walk(self) -> FunctionEvents:
        self._walk_block(self.fi.node.body)
        return self.out

    def _walk_block(self, stmts: List[ast.stmt]) -> None:
        # bare acquires stay held until a release() statement pops them (at
        # any block level) or the function ends — the deliberate
        # over-approximation: "may still be held"
        for stmt in stmts:
            lock = self._bare_acquire(stmt)
            if lock is not None:
                call = stmt.value  # type: ignore[attr-defined]
                self._push(
                    lock, stmt.lineno, blocking_noarg=not _acquire_is_timed(call)
                )
                self._visit_exprs(stmt)
                continue
            rel = self._bare_release(stmt)
            if rel is not None:
                self._pop(rel)
                self._visit_exprs(stmt)
                continue
            self._walk_stmt(stmt)

    def _bare_acquire(self, stmt: ast.stmt) -> Optional[str]:
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "acquire"
        ):
            return self._lock_of(stmt.value.func.value)
        return None

    def _bare_release(self, stmt: ast.stmt) -> Optional[str]:
        if (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr == "release"
        ):
            return self._lock_of(stmt.value.func.value)
        return None

    def _walk_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            return  # nested defs analyzed as their own functions
        if isinstance(stmt, ast.Assign):
            self._track_assign(stmt)
            self._visit_exprs(stmt)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            entered: List[str] = []
            for item in stmt.items:
                for node in _walk_no_lambda(item.context_expr):
                    if isinstance(node, ast.Call):
                        self._note_call(node)
                lock = self._lock_of(item.context_expr)
                if lock is not None and self._push(lock, stmt.lineno):
                    entered.append(lock)
            self._walk_block(stmt.body)
            for lock in entered:
                self._pop(lock)
            return
        if isinstance(stmt, ast.Try):
            self._walk_block(stmt.body)
            for handler in stmt.handlers:
                self._walk_block(handler.body)
            self._walk_block(stmt.orelse)
            self._walk_block(stmt.finalbody)
            return
        if isinstance(stmt, (ast.If,)):
            self._visit_exprs(stmt.test)
            self._walk_block(stmt.body)
            self._walk_block(stmt.orelse)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._visit_exprs(stmt.iter)
            self._walk_block(stmt.body)
            self._walk_block(stmt.orelse)
            return
        if isinstance(stmt, ast.While):
            self._visit_exprs(stmt.test)
            self._walk_block(stmt.body)
            self._walk_block(stmt.orelse)
            return
        self._visit_exprs(stmt)

    def _track_assign(self, stmt: ast.Assign) -> None:
        if _is_lock_factory_call(stmt.value):
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.local_locks[tgt.id] = (
                        f"{self.fi.module.relpath}::{self.fi.qualname}.{tgt.id}"
                    )
            return
        lock = self._lock_of(stmt.value)
        if lock is not None:
            for tgt in stmt.targets:
                if isinstance(tgt, ast.Name):
                    self.aliases[tgt.id] = lock

    # -- expression-level events -------------------------------------------
    def _visit_exprs(self, node: ast.AST) -> None:
        for sub in _walk_no_lambda(node):
            if isinstance(sub, ast.Call):
                self._note_call(sub)

    def _note_call(self, call: ast.Call) -> None:
        func = call.func
        held = tuple(self.held)
        if isinstance(func, ast.Attribute):
            meth = func.attr
            recv = _expr_display(func.value)
            if meth in RESOLVE_METHODS or (meth == "cancel" and _looks_like_future(recv)):
                # skip the lock-shaped false positive: event.set_result doesn't
                # exist, but lock.acquire/release were handled above
                self.out.resolves.append(ResolveEvent(meth, recv, held, call.lineno))
            if meth == "add_done_callback" and call.args:
                targets = self._callback_targets(call.args[0])
                self.out.registers.append(RegisterEvent(targets, call.lineno))
            if meth == "acquire":
                lock = self._lock_of(func.value)
                if lock is not None and lock not in self.held:
                    # non-statement acquire (e.g. `if lock.acquire(timeout=t):`)
                    self.out.acquires.append(
                        AcquireEvent(
                            lock,
                            held,
                            call.lineno,
                            blocking_noarg=not _acquire_is_timed(call),
                        )
                    )
        targets = self.project.resolve_call(self.fi, call, self.local_types)
        if targets:
            self.out.calls.append(
                CallEvent(call, targets, held, call.lineno, _call_display(call))
            )

    def _callback_targets(self, arg: ast.AST) -> List[FunctionInfo]:
        if isinstance(arg, ast.Lambda):
            out: List[FunctionInfo] = []
            for sub in ast.walk(arg.body):
                if isinstance(sub, ast.Call):
                    out.extend(self.project.resolve_call(self.fi, sub, self.local_types))
            return out
        if isinstance(arg, ast.Call):  # functools.partial(f, ...)
            disp = _call_display(arg)
            if disp.endswith("partial") and arg.args:
                return self._callback_targets(arg.args[0])
            return []
        return self.project.resolve_callable(self.fi, arg, self.local_types)


def extract_events(project: Project) -> Dict[str, FunctionEvents]:
    """display-qualname -> events, for every function in the project."""
    resolver = LockResolver(project)
    out: Dict[str, FunctionEvents] = {}
    for m in project.modules:
        for fi in m.functions.values():
            out[fi.display] = _FunctionWalker(project, resolver, fi).walk()
    return out
