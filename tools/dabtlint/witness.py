"""Runtime lock-order witness — the dynamic half of DABT101/DABT102.

Opt-in (``DABT_LOCK_WITNESS=1``): the repo-root conftest registers
:class:`WitnessPlugin`, which monkeypatches ``threading.Lock``/``RLock`` so
that every lock *created by project code* (caller filename under the project
root) is wrapped.  The wrapper maintains a per-thread held stack and a global
acquisition-order graph over lock *classes* — locks are classed by their
creation site (``path::assignment-target``), so every ``RequestScheduler``
instance shares one node, exactly like FreeBSD WITNESS lock classes.

Recorded violations (reported at session end; the session FAILS on any):

- **lock-order cycle** — acquiring B while holding A when the graph already
  knows a B -> ... -> A path.  Orders are recorded *before* blocking, so two
  suites that each take only one side of an ABBA pair still convict the pair.
- **same-class nesting** — acquiring a lock of class A while holding a
  *different instance* of A (the scheduler<->scheduler double-death deadlock
  of PR 7: no single-threaded order exists between peer instances).
- **future resolved under a held lock** — ``Future.set_result`` /
  ``set_exception`` / ``cancel`` called while the thread holds any witnessed
  lock whose class is not in the baseline's witness allowlist
  (done-callbacks run synchronously on the resolving thread; see DABT102).

The static pass proves what the AST can see; the witness confirms what the
test suites actually execute — including orders through jitted callbacks and
dynamic dispatch the AST cannot resolve.
"""

from __future__ import annotations

import linecache
import os
import re
import sys
import threading
import traceback
from concurrent.futures import Future
from typing import Dict, List, Optional, Tuple

_ASSIGN_RE = re.compile(r"([A-Za-z_][\w\.]*)\s*=\s*threading\.(?:R?Lock)\s*\(")


class WitnessViolation:
    def __init__(self, kind: str, description: str, stack: str):
        self.kind = kind
        self.description = description
        self.stack = stack

    def __repr__(self):
        return f"<WitnessViolation {self.kind}: {self.description}>"

    def render(self) -> str:
        return f"[{self.kind}] {self.description}\n{self.stack}"


def _stack_summary(limit: int = 14) -> str:
    frames = traceback.extract_stack()[:-3]
    interesting = [
        f
        for f in frames
        if "site-packages" not in f.filename and os.sep + "lib" + os.sep not in f.filename
    ] or frames
    return "".join(
        f"    {os.path.basename(f.filename)}:{f.lineno} in {f.name}\n"
        for f in interesting[-limit:]
    )


class _Held:
    __slots__ = ("cls", "instance", "count")

    def __init__(self, cls: str, instance: int):
        self.cls = cls
        self.instance = instance
        self.count = 1


class LockOrderWitness:
    """The global recorder.  One instance per installed session."""

    def __init__(
        self,
        project_root: str,
        *,
        allowed_held: Optional[Dict[str, str]] = None,
        real_lock_factory=None,
    ):
        self.project_root = os.path.abspath(project_root)
        # lock classes allowed to be held across a Future resolution,
        # name -> justification (the baseline's "witness" section)
        self.allowed_held = dict(allowed_held or {})
        self._factory = real_lock_factory or threading.Lock
        self._mu = self._factory()  # a REAL lock: guards graph + violations
        self._graph: Dict[str, set] = {}
        self._edge_sites: Dict[Tuple[str, str], str] = {}
        self.lock_classes: Dict[str, int] = {}  # class name -> instances made
        self.violations: List[WitnessViolation] = []
        self._dedupe: set = set()
        self._tls = threading.local()

    # ------------------------------------------------------------- held stack
    def _held(self) -> List[_Held]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def held_classes(self) -> List[str]:
        return [h.cls for h in self._held()]

    # ---------------------------------------------------------------- events
    def note_acquire(
        self, cls: str, instance: int, reentrant: bool, blocking: bool = True
    ) -> None:
        held = self._held()
        for h in held:
            if h.instance == instance:
                if reentrant:
                    h.count += 1
                    return
                if blocking:
                    # non-reentrant BLOCKING re-acquire of the same instance:
                    # a guaranteed self-deadlock the real acquire demonstrates
                    # (a try-acquire just returns False — legal, not flagged)
                    self._record(
                        "self-deadlock",
                        f"thread re-acquires non-reentrant lock {cls}",
                    )
                break
        else:
            for h in held:
                if h.cls == cls and h.instance != instance:
                    self._record(
                        "same-class-nesting",
                        f"acquiring {cls} while holding a different instance "
                        f"of {cls} — peer instances have no global order "
                        "(two threads nesting opposite instances deadlock)",
                    )
            self._note_edges(cls, [h.cls for h in held if h.cls != cls])
            held.append(_Held(cls, instance))
            return
        held.append(_Held(cls, instance))

    def note_release(self, instance: int) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i].instance == instance:
                held[i].count -= 1
                if held[i].count <= 0:
                    del held[i]
                return

    def note_acquire_failed(self, instance: int) -> None:
        # timed acquire that returned False: the edge stays recorded (the
        # ORDER was attempted) but the lock is not held
        self.note_release(instance)

    def _note_edges(self, new: str, held_classes: List[str]) -> None:
        if not held_classes:
            return
        with self._mu:
            for h in held_classes:
                if (h, new) in self._edge_sites:
                    continue
                # does the reverse path already exist?  check BEFORE adding,
                # so the cycle is reported exactly once, at the closing edge
                path = self._path(new, h)
                self._graph.setdefault(h, set()).add(new)
                self._graph.setdefault(new, set())
                self._edge_sites[(h, new)] = _stack_summary()
                if path is not None:
                    cyc = " -> ".join([h, new] + path[1:])
                    first = self._edge_sites.get(
                        (new, path[1]) if len(path) > 1 else (new, h), ""
                    )
                    self._record_unlocked(
                        "lock-order-cycle",
                        f"acquisition order cycle: {cyc} (this thread took "
                        f"{h} then {new}; an earlier order took the reverse "
                        "path)",
                        extra=f"  reverse-order site:\n{first}" if first else "",
                    )

    def _path(self, src: str, dst: str) -> Optional[List[str]]:
        """Path src -> ... -> dst in the current graph (call with _mu held)."""
        if src == dst:
            return [src]
        seen = {src}
        stack = [(src, [src])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(self._graph.get(node, ())):
                if nxt == dst:
                    return path + [nxt]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def note_future_resolution(self, what: str) -> None:
        held = [
            h.cls for h in self._held() if h.cls not in self.allowed_held
        ]
        if held:
            self._record(
                "future-under-lock",
                f"Future.{what}() while holding {', '.join(sorted(set(held)))}"
                " — done-callbacks run synchronously under that lock",
            )

    # ------------------------------------------------------------- recording
    def _record(self, kind: str, description: str, extra: str = "") -> None:
        with self._mu:
            self._record_unlocked(kind, description, extra=extra)

    def _record_unlocked(self, kind: str, description: str, extra: str = "") -> None:
        key = (kind, description)
        if key in self._dedupe:
            return
        self._dedupe.add(key)
        self.violations.append(
            WitnessViolation(kind, description, _stack_summary() + extra)
        )

    # ---------------------------------------------------------------- naming
    def class_name_for_creation(self, filename: str, lineno: int) -> str:
        rel = os.path.relpath(filename, os.path.dirname(self.project_root)).replace(
            os.sep, "/"
        )
        line = linecache.getline(filename, lineno)
        m = _ASSIGN_RE.search(line)
        target = m.group(1) if m else f"line{lineno}"
        name = f"{rel}::{target}"
        with self._mu:
            self.lock_classes[name] = self.lock_classes.get(name, 0) + 1
        return name

    def stats(self) -> dict:
        with self._mu:
            return {
                "lock_classes": len(self.lock_classes),
                "order_edges": len(self._edge_sites),
                "violations": len(self.violations),
            }


class WitnessedLock:
    """Wraps a real ``threading.Lock``/``RLock`` with witness bookkeeping."""

    __slots__ = ("_lock", "_witness", "_cls", "_reentrant")

    def __init__(self, real, witness: LockOrderWitness, cls: str, reentrant: bool):
        self._lock = real
        self._witness = witness
        self._cls = cls
        self._reentrant = reentrant

    def acquire(self, *args, **kwargs):
        # record the attempted ORDER before blocking: a real ABBA interleaving
        # hangs in the real acquire below, but the witness has already
        # convicted the order by then
        blocking = bool(args[0]) if args else bool(kwargs.get("blocking", True))
        self._witness.note_acquire(
            self._cls, id(self), self._reentrant, blocking=blocking
        )
        ok = self._lock.acquire(*args, **kwargs)
        if not ok:
            self._witness.note_acquire_failed(id(self))
        return ok

    def release(self):
        self._lock.release()
        self._witness.note_release(id(self))

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()

    def __repr__(self):
        return f"<WitnessedLock {self._cls} of {self._lock!r}>"


_installed: Optional[dict] = None


def install(witness: LockOrderWitness) -> LockOrderWitness:
    """Patch threading.Lock/RLock and Future resolution.  Locks created by
    files under ``witness.project_root`` are wrapped; everything else (stdlib,
    jax, site-packages) gets the real thing."""
    global _installed
    if _installed is not None:
        raise RuntimeError("lock-order witness already installed")
    real_lock = threading.Lock
    real_rlock = threading.RLock

    def make_lock():
        caller = sys._getframe(1)
        if caller.f_code.co_filename.startswith(witness.project_root):
            cls = witness.class_name_for_creation(
                caller.f_code.co_filename, caller.f_lineno
            )
            return WitnessedLock(real_lock(), witness, cls, reentrant=False)
        return real_lock()

    def make_rlock():
        caller = sys._getframe(1)
        if caller.f_code.co_filename.startswith(witness.project_root):
            cls = witness.class_name_for_creation(
                caller.f_code.co_filename, caller.f_lineno
            )
            return WitnessedLock(real_rlock(), witness, cls, reentrant=True)
        return real_rlock()

    real_set_result = Future.set_result
    real_set_exception = Future.set_exception
    real_cancel = Future.cancel

    def set_result(self, result):
        witness.note_future_resolution("set_result")
        return real_set_result(self, result)

    def set_exception(self, exc):
        witness.note_future_resolution("set_exception")
        return real_set_exception(self, exc)

    def cancel(self):
        cancelled = real_cancel(self)
        if cancelled:
            # only a SUCCESSFUL cancel runs done-callbacks; a False return
            # (already running/done) invokes nothing and is hazard-free
            witness.note_future_resolution("cancel")
        return cancelled

    threading.Lock = make_lock
    threading.RLock = make_rlock
    Future.set_result = set_result
    Future.set_exception = set_exception
    Future.cancel = cancel
    _installed = {
        "witness": witness,
        "Lock": real_lock,
        "RLock": real_rlock,
        "set_result": real_set_result,
        "set_exception": real_set_exception,
        "cancel": real_cancel,
    }
    return witness


def uninstall() -> Optional[LockOrderWitness]:
    global _installed
    if _installed is None:
        return None
    threading.Lock = _installed["Lock"]
    threading.RLock = _installed["RLock"]
    Future.set_result = _installed["set_result"]
    Future.set_exception = _installed["set_exception"]
    Future.cancel = _installed["cancel"]
    witness = _installed["witness"]
    _installed = None
    return witness


def load_witness_allowlist(baseline_path: str) -> Dict[str, str]:
    import json

    if not os.path.exists(baseline_path):
        return {}
    try:
        with open(baseline_path, "r", encoding="utf-8") as fh:
            return dict(json.load(fh).get("witness", {}))
    except (ValueError, OSError):
        return {}


def pytest_configure(config):
    """Module-level hook so ``pytest -p dabtlint.witness`` works without the
    repo-root conftest (subprocess tests, other repos).  Env-driven:
    DABT_LOCK_WITNESS=1 arms it, DABT_WITNESS_ROOT names the project root,
    DABT_WITNESS_BASELINE (optional) the baseline with the witness
    allowlist."""
    if os.environ.get("DABT_LOCK_WITNESS") != "1":
        return
    if config.pluginmanager.has_plugin("dabt-lock-witness"):
        return
    root = os.environ.get("DABT_WITNESS_ROOT")
    if not root:
        return
    config.pluginmanager.register(
        WitnessPlugin(root, os.environ.get("DABT_WITNESS_BASELINE")),
        "dabt-lock-witness",
    )


class WitnessPlugin:
    """Pytest plugin: install at configure, report + fail at session end.

    Registered by the repo-root conftest when ``DABT_LOCK_WITNESS=1`` — see
    docs/STATIC_ANALYSIS.md for the local workflow."""

    def __init__(self, project_root: str, baseline_path: Optional[str] = None):
        self.witness = LockOrderWitness(
            project_root,
            allowed_held=(
                load_witness_allowlist(baseline_path) if baseline_path else {}
            ),
        )

    def pytest_configure(self, config):
        install(self.witness)

    def pytest_sessionfinish(self, session, exitstatus):
        uninstall()
        if self.witness.violations:
            # wrap_session re-reads session.exitstatus after the finally that
            # fires this hook, so setting it here fails the run
            session.exitstatus = 1

    def pytest_terminal_summary(self, terminalreporter):
        tr = terminalreporter
        stats = self.witness.stats()
        tr.section("lock-order witness (DABT_LOCK_WITNESS=1)")
        tr.line(
            f"{stats['lock_classes']} project lock class(es), "
            f"{stats['order_edges']} acquisition-order edge(s), "
            f"{stats['violations']} violation(s)"
        )
        for v in self.witness.violations:
            tr.line("")
            tr.line(v.render())
        if self.witness.violations:
            tr.line("")
            tr.line(
                "the session FAILS on witness violations; accepted lock "
                "classes live in tools/dabtlint/baseline.json ('witness' "
                "section, justification required)"
            )
