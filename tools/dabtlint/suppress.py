"""Inline suppression: ``# dabtlint: ignore[DABT102] <reason>``.

A suppression comment applies to findings on its own line, or — when the
comment stands alone on a line — to the first following non-comment line.
The reason is mandatory: a bare ``ignore[...]`` suppresses nothing and is
itself reported, so every silenced finding carries its WHY in the source.
"""

from __future__ import annotations

import re
from typing import Dict, List, Sequence, Set, Tuple

from .findings import Finding

_RE = re.compile(r"#\s*dabtlint:\s*ignore\[([A-Za-z0-9_,\s]+)\]\s*(.*?)\s*$")


def _parse_line(line: str) -> Tuple[Set[str], str] | None:
    m = _RE.search(line)
    if not m:
        return None
    codes = {c.strip().upper() for c in m.group(1).split(",") if c.strip()}
    return codes, m.group(2).strip()


def suppressions_for(lines: Sequence[str]) -> Tuple[Dict[int, Set[str]], List[Tuple[int, str]]]:
    """lineno(1-based) -> suppressed codes; plus [(lineno, problem)] for
    malformed suppressions (missing reason)."""
    out: Dict[int, Set[str]] = {}
    bad: List[Tuple[int, str]] = []
    for i, line in enumerate(lines, start=1):
        parsed = _parse_line(line)
        if parsed is None:
            continue
        codes, reason = parsed
        if not reason:
            bad.append((i, "suppression without a reason (ignored)"))
            continue
        target = i
        if line.lstrip().startswith("#"):
            # standalone comment: applies to the next non-comment source line
            j = i + 1
            while j <= len(lines) and (
                not lines[j - 1].strip() or lines[j - 1].lstrip().startswith("#")
            ):
                j += 1
            target = j
        out.setdefault(target, set()).update(codes)
        if target != i:
            out.setdefault(i, set()).update(codes)
    return out, bad


def apply_suppressions(
    findings: Sequence[Finding], lines_by_module: Dict[str, Sequence[str]]
) -> Tuple[List[Finding], List[Finding], List[Tuple[str, int, str]]]:
    """(kept, suppressed, problems)."""
    cache: Dict[str, Tuple[Dict[int, Set[str]], List[Tuple[int, str]]]] = {}
    problems: List[Tuple[str, int, str]] = []
    for module, lines in lines_by_module.items():
        cache[module] = suppressions_for(lines)
        for lineno, why in cache[module][1]:
            problems.append((module, lineno, why))
    kept: List[Finding] = []
    suppressed: List[Finding] = []
    for f in findings:
        supp = cache.get(f.module, ({}, []))[0]
        if f.code in supp.get(f.line, set()):
            suppressed.append(f)
        else:
            kept.append(f)
    return kept, suppressed, problems
