"""Project model: modules, symbol tables, and a conservative call graph.

dabtlint is *project-aware*, not whole-program: it parses every ``.py`` file
under the analyzed roots, builds per-module symbol tables (functions, classes,
imports, lock-holding attributes), and resolves calls only when it can name
the target with confidence:

- ``self.method(...)``         -> method of the same class (or a project base)
- ``name(...)``                -> function in the same module, or one imported
                                  ``from project.module import name``
- ``mod.func(...)``            -> function of an imported project module
- ``self.attr.method(...)``    -> method of ``attr``'s class, when the class is
                                  known from a constructor assignment
                                  (``self.attr = ClassName(...)``) or a
                                  parameter/attribute annotation
- ``var.method(...)``          -> same, for locals assigned from a project
                                  class constructor in the same function

Anything else is unresolved and contributes no call edge — missing an edge
can miss a finding, but never invents one.  The same discipline applies to
lock identities (see :mod:`dabtlint.locks`).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Sequence, Tuple

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


@dataclasses.dataclass
class FunctionInfo:
    qualname: str  # "Class.method", "func", or "outer.<locals>.inner"
    node: ast.AST  # FunctionDef | AsyncFunctionDef
    module: "ModuleInfo"
    cls: Optional[str]
    is_async: bool

    @property
    def display(self) -> str:
        return f"{self.module.relpath}::{self.qualname}"


@dataclasses.dataclass
class ClassInfo:
    name: str
    bases: List[str]
    methods: Dict[str, FunctionInfo]
    # attribute -> (module, classname) of the attribute's project class
    attr_types: Dict[str, Tuple["ModuleInfo", str]]
    # attributes assigned threading.Lock()/RLock()/Condition()/Semaphore()
    lock_attrs: Dict[str, int]  # attr -> lineno of creation


@dataclasses.dataclass
class ModuleInfo:
    path: str
    relpath: str  # '/'-separated, relative to the analysis root's parent
    modname: str  # dotted module name ("pkg.serving.engine")
    tree: ast.Module
    lines: List[str]
    functions: Dict[str, FunctionInfo] = dataclasses.field(default_factory=dict)
    classes: Dict[str, ClassInfo] = dataclasses.field(default_factory=dict)
    # local name -> fully dotted target ("pkg.mod" or "pkg.mod.attr")
    imports: Dict[str, str] = dataclasses.field(default_factory=dict)
    module_locks: Dict[str, int] = dataclasses.field(default_factory=dict)

    def __hash__(self):  # identity hash: one object per parsed file
        return id(self)


def _is_lock_factory_call(node: ast.AST) -> bool:
    """threading.Lock() / threading.RLock() / Lock() (imported) ..."""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr in LOCK_FACTORIES:
        return isinstance(f.value, ast.Name) and f.value.id == "threading"
    if isinstance(f, ast.Name) and f.id in LOCK_FACTORIES:
        return True
    return False


def _annotation_class_name(ann: ast.AST) -> Optional[str]:
    """Extract a plain class name out of `X`, `"X"`, `Optional[X]`,
    `Optional["X"]`.  Anything fancier resolves to None (no type info)."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.strip()
        return name if name.isidentifier() else None
    if isinstance(ann, ast.Name):
        return ann.id
    if isinstance(ann, ast.Subscript):
        base = ann.value
        base_name = base.attr if isinstance(base, ast.Attribute) else getattr(base, "id", "")
        if base_name == "Optional":
            return _annotation_class_name(ann.slice)
    return None


class Project:
    """All parsed modules plus name-resolution helpers."""

    def __init__(self, root_label: str = ""):
        self.modules: List[ModuleInfo] = []
        self.by_modname: Dict[str, ModuleInfo] = {}
        self.root_label = root_label

    # ----------------------------------------------------------------- loading
    @classmethod
    def load(cls, roots: Sequence[str], *, base_dir: Optional[str] = None) -> "Project":
        """Parse every .py file under each root (a package dir or a single
        file).  ``base_dir`` anchors relpaths/modnames; defaults to each
        root's parent so `pkg/sub/mod.py` becomes modname `pkg.sub.mod`."""
        proj = cls()
        for root in roots:
            root = os.path.abspath(root)
            anchor = os.path.abspath(base_dir) if base_dir else os.path.dirname(root)
            if os.path.isfile(root):
                proj._load_file(root, anchor)
                continue
            for dirpath, dirnames, filenames in os.walk(root):
                dirnames[:] = sorted(
                    d for d in dirnames if d not in {"__pycache__", ".git"}
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        proj._load_file(os.path.join(dirpath, fn), anchor)
        proj._index()
        return proj

    def _load_file(self, path: str, anchor: str) -> None:
        rel = os.path.relpath(path, anchor).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast.parse(src, filename=path)
        except (SyntaxError, UnicodeDecodeError):
            return  # dabtlint is not a syntax checker; skip unparsable files
        modname = rel[:-3].replace("/", ".")
        if modname.endswith(".__init__"):
            modname = modname[: -len(".__init__")]
        self.modules.append(
            ModuleInfo(
                path=path,
                relpath=rel,
                modname=modname,
                tree=tree,
                lines=src.splitlines(),
            )
        )

    # ---------------------------------------------------------------- indexing
    def _index(self) -> None:
        for m in self.modules:
            self.by_modname[m.modname] = m
        for m in self.modules:
            self._index_module(m)
        # attribute types need imports + classes of every module, so a second
        # pass resolves them once the whole project is indexed
        for m in self.modules:
            for ci in m.classes.values():
                self._index_attr_types(m, ci)

    def _index_module(self, m: ModuleInfo) -> None:
        # imports anywhere in the module (function-local "from .engine import
        # _safe_resolve" is the repo's circular-import idiom — those names
        # must resolve or the interprocedural summaries go blind)
        for node in ast.walk(m.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                self._index_import(m, node)
        for node in m.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(m, node, prefix="", cls=None)
            elif isinstance(node, ast.ClassDef):
                self._index_class(m, node)
            elif isinstance(node, ast.Assign):
                if _is_lock_factory_call(node.value):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            m.module_locks[tgt.id] = node.lineno

    def _index_import(self, m: ModuleInfo, node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    m.imports[alias.asname] = alias.name
                else:
                    root = alias.name.split(".")[0]
                    m.imports[root] = root
        elif isinstance(node, ast.ImportFrom):
            if node.level:  # relative import: resolve against this module
                parts = m.modname.split(".")
                # level 1 = same package; __init__ modnames already dropped
                base = parts[: len(parts) - node.level]
                prefix = ".".join(base + ([node.module] if node.module else []))
            else:
                prefix = node.module or ""
            for alias in node.names:
                if alias.name == "*":
                    continue
                target = f"{prefix}.{alias.name}" if prefix else alias.name
                m.imports[alias.asname or alias.name] = target

    def _index_function(
        self, m: ModuleInfo, node: ast.AST, *, prefix: str, cls: Optional[str]
    ) -> None:
        qualname = f"{prefix}{node.name}" if prefix else node.name
        fi = FunctionInfo(
            qualname=qualname,
            node=node,
            module=m,
            cls=cls,
            is_async=isinstance(node, ast.AsyncFunctionDef),
        )
        m.functions[qualname] = fi
        if cls is not None and "." in qualname and "<locals>" not in qualname:
            m.classes[cls].methods[node.name] = fi
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(
                    m, child, prefix=f"{qualname}.<locals>.", cls=cls
                )

    def _index_class(self, m: ModuleInfo, node: ast.ClassDef) -> None:
        bases = []
        for b in node.bases:
            if isinstance(b, ast.Name):
                bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                bases.append(b.attr)
        ci = ClassInfo(node.name, bases, {}, {}, {})
        m.classes[node.name] = ci
        for child in node.body:
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._index_function(m, child, prefix=f"{node.name}.", cls=node.name)
            elif isinstance(child, ast.Assign) and _is_lock_factory_call(child.value):
                for tgt in child.targets:
                    if isinstance(tgt, ast.Name):
                        ci.lock_attrs[tgt.id] = child.lineno

    def _index_attr_types(self, m: ModuleInfo, ci: ClassInfo) -> None:
        """self.X = ClassName(...) / self.X = <param annotated ClassName> /
        self.X = threading.Lock() inside any method of the class."""
        for fi in ci.methods.values():
            params_by_name = {}
            args = fi.node.args
            for a in list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs):
                params_by_name[a.arg] = _annotation_class_name(a.annotation)
            for stmt in ast.walk(fi.node):
                if not isinstance(stmt, ast.Assign):
                    continue
                for tgt in stmt.targets:
                    if not (
                        isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"
                    ):
                        continue
                    attr = tgt.attr
                    if _is_lock_factory_call(stmt.value):
                        ci.lock_attrs.setdefault(attr, stmt.lineno)
                        continue
                    resolved = None
                    if isinstance(stmt.value, ast.Call):
                        resolved = self.resolve_class(m, stmt.value.func)
                    elif isinstance(stmt.value, ast.Name):
                        ann = params_by_name.get(stmt.value.id)
                        if ann:
                            resolved = self.resolve_class_by_name(m, ann)
                    if resolved is not None:
                        ci.attr_types.setdefault(attr, resolved)

    # -------------------------------------------------------------- resolution
    def resolve_module(self, m: ModuleInfo, name: str) -> Optional[ModuleInfo]:
        target = m.imports.get(name, name)
        return self.by_modname.get(target)

    def resolve_class_by_name(
        self, m: ModuleInfo, name: str
    ) -> Optional[Tuple[ModuleInfo, str]]:
        if name in m.classes:
            return (m, name)
        target = m.imports.get(name)
        if target and "." in target:
            modname, _, cls_name = target.rpartition(".")
            tm = self.by_modname.get(modname)
            if tm is not None and cls_name in tm.classes:
                return (tm, cls_name)
        return None

    def resolve_class(
        self, m: ModuleInfo, func_expr: ast.AST
    ) -> Optional[Tuple[ModuleInfo, str]]:
        """The class a constructor expression names (Name or mod.Name)."""
        if isinstance(func_expr, ast.Name):
            return self.resolve_class_by_name(m, func_expr.id)
        if isinstance(func_expr, ast.Attribute) and isinstance(func_expr.value, ast.Name):
            tm = self.resolve_module(m, func_expr.value.id)
            if tm is not None and func_expr.attr in tm.classes:
                return (tm, func_expr.attr)
        return None

    def class_method(
        self, mod: ModuleInfo, cls_name: str, meth: str, _seen=None
    ) -> Optional[FunctionInfo]:
        """Method lookup through project base classes (by name)."""
        _seen = _seen or set()
        if (id(mod), cls_name) in _seen:
            return None
        _seen.add((id(mod), cls_name))
        ci = mod.classes.get(cls_name)
        if ci is None:
            return None
        if meth in ci.methods:
            return ci.methods[meth]
        for base in ci.bases:
            resolved = self.resolve_class_by_name(mod, base)
            if resolved is not None:
                found = self.class_method(resolved[0], resolved[1], meth, _seen)
                if found is not None:
                    return found
        return None

    def _local_var_types(self, fi: FunctionInfo) -> Dict[str, Tuple[ModuleInfo, str]]:
        """name -> project class, for `v = ClassName(...)` locals."""
        out: Dict[str, Tuple[ModuleInfo, str]] = {}
        for stmt in ast.walk(fi.node):
            if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
                resolved = self.resolve_class(fi.module, stmt.value.func)
                if resolved is None:
                    continue
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.setdefault(tgt.id, resolved)
        return out

    def resolve_call(
        self,
        fi: FunctionInfo,
        call: ast.Call,
        local_types: Optional[Dict[str, Tuple[ModuleInfo, str]]] = None,
    ) -> List[FunctionInfo]:
        """Project functions a call may target; [] when unresolvable."""
        return self.resolve_callable(fi, call.func, local_types)

    def resolve_callable(
        self,
        fi: FunctionInfo,
        func: ast.AST,
        local_types: Optional[Dict[str, Tuple[ModuleInfo, str]]] = None,
    ) -> List[FunctionInfo]:
        m = fi.module
        if isinstance(func, ast.Name):
            name = func.id
            # nested function defined in an enclosing scope of this function
            scope = fi.qualname
            while True:
                nested = m.functions.get(f"{scope}.<locals>.{name}")
                if nested is not None:
                    return [nested]
                if ".<locals>." not in scope:
                    break
                scope = scope.rsplit(".<locals>.", 1)[0]
            if name in m.functions:
                return [m.functions[name]]
            cls = self.resolve_class_by_name(m, name)
            if cls is not None:
                init = self.class_method(cls[0], cls[1], "__init__")
                return [init] if init is not None else []
            target = m.imports.get(name)
            if target and "." in target:
                modname, _, fname = target.rpartition(".")
                tm = self.by_modname.get(modname)
                if tm is not None and fname in tm.functions:
                    return [tm.functions[fname]]
            return []
        if not isinstance(func, ast.Attribute):
            return []
        meth = func.attr
        value = func.value
        if isinstance(value, ast.Name):
            if value.id == "self" and fi.cls is not None:
                found = self.class_method(m, fi.cls, meth)
                return [found] if found is not None else []
            tm = self.resolve_module(m, value.id)
            if tm is not None and meth in tm.functions:
                return [tm.functions[meth]]
            ltypes = local_types or {}
            if value.id in ltypes:
                cmod, cname = ltypes[value.id]
                found = self.class_method(cmod, cname, meth)
                return [found] if found is not None else []
            cls = self.resolve_class_by_name(m, value.id)
            if cls is not None:  # ClassName.method(...) — unbound/static use
                found = self.class_method(cls[0], cls[1], meth)
                return [found] if found is not None else []
            return []
        if (
            isinstance(value, ast.Attribute)
            and isinstance(value.value, ast.Name)
            and value.value.id == "self"
            and fi.cls is not None
        ):
            ci = m.classes.get(fi.cls)
            if ci is not None and value.attr in ci.attr_types:
                cmod, cname = ci.attr_types[value.attr]
                found = self.class_method(cmod, cname, meth)
                return [found] if found is not None else []
        return []
