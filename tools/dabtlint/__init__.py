"""dabtlint — concurrency- and hot-path-aware static analysis for the
django-assistant-bot-tpu serving stack, plus a runtime lock-order witness.

Checkers (docs/STATIC_ANALYSIS.md has the full catalog with the real bugs
that motivated each):

- DABT101  lock-order cycles (with Future->done-callback edges)
- DABT102  Future resolved while a lock is held
- DABT103  blocking calls inside ``async def``
- DABT104  device->host syncs reachable from the decode hot paths
- DABT105  raw time in clock-disciplined serving modules

Stdlib-only on purpose: the CI gate runs before any dependency install.
"""

from .baseline import Baseline, BaselineError
from .checks import Analysis, run_analysis
from .findings import CHECKERS, Finding
from .project import Project

__all__ = [
    "Analysis",
    "Baseline",
    "BaselineError",
    "CHECKERS",
    "Finding",
    "Project",
    "run_analysis",
]
