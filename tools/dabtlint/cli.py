"""dabtlint command line.

    dabtlint django_assistant_bot_tpu/                 # gate: exit 1 on new findings
    dabtlint pkg/ --codes DABT101,DABT102              # subset of checkers
    dabtlint pkg/ --write-baseline                     # refresh the baseline (TODO stubs!)
    dabtlint pkg/ --format json                        # machine-readable
    dabtlint pkg/ --show-accepted                      # print baselined findings too

Exit codes: 0 clean (possibly with baselined/suppressed findings), 1 new
findings, 2 configuration error (bad baseline, bad code list).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Sequence

from .baseline import Baseline, BaselineError
from .checks import Analysis
from .findings import Finding, parse_code_list
from .project import Project
from .suppress import apply_suppressions

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def analyze_paths(
    paths: Sequence[str],
    *,
    base_dir: Optional[str] = None,
    select=None,
):
    project = Project.load(paths, base_dir=base_dir)
    findings = Analysis(project).run(select)
    lines_by_module: Dict[str, List[str]] = {m.relpath: m.lines for m in project.modules}
    return project, findings, lines_by_module


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dabtlint",
        description="concurrency- and hot-path-aware static analysis for the "
        "django-assistant-bot-tpu serving stack (DABT101..DABT105)",
    )
    ap.add_argument("paths", nargs="+", help="package directories or files to analyze")
    ap.add_argument(
        "--baseline",
        default=DEFAULT_BASELINE,
        help="baseline JSON of accepted findings (default: tools/dabtlint/baseline.json)",
    )
    ap.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline entirely (report every finding as new)",
    )
    ap.add_argument(
        "--write-baseline",
        action="store_true",
        help="write the current finding set to the baseline file; NEW entries "
        "get a 'TODO' justification stub the loader refuses, so every "
        "acceptance still needs a human sentence",
    )
    ap.add_argument("--codes", default="all", help="comma-separated checker codes to run")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument(
        "--show-accepted",
        action="store_true",
        help="also print findings matched by the baseline",
    )
    ap.add_argument("--no-hints", action="store_true", help="omit fix-it hints")
    args = ap.parse_args(argv)

    try:
        select = parse_code_list(args.codes)
    except ValueError as e:
        print(f"dabtlint: {e}", file=sys.stderr)
        return 2

    _, findings, lines_by_module = analyze_paths(args.paths, select=select)
    kept, suppressed, problems = apply_suppressions(findings, lines_by_module)

    baseline = None
    if not args.no_baseline:
        try:
            baseline = Baseline.load(args.baseline)
        except BaselineError as e:
            print(f"dabtlint: {e}", file=sys.stderr)
            return 2

    if args.write_baseline:
        n = Baseline.write(args.baseline, kept, keep=baseline)
        print(
            f"dabtlint: wrote {n} baseline entr{'y' if n == 1 else 'ies'} to "
            f"{args.baseline} — fill in every TODO justification before "
            "committing (the loader rejects stubs)"
        )
        return 0

    if baseline is not None:
        new, accepted, stale = baseline.split(kept)
    else:
        new, accepted, stale = list(kept), [], []

    if args.format == "json":
        print(
            json.dumps(
                {
                    "new": [f.__dict__ for f in new],
                    "accepted": [f.__dict__ for f in accepted],
                    "suppressed": [f.__dict__ for f in suppressed],
                    "stale_baseline_entries": stale,
                    "suppression_problems": [
                        {"module": m, "line": line, "problem": p}
                        for m, line, p in problems
                    ],
                },
                indent=2,
            )
        )
        return 1 if new else 0

    for f in new:
        print(f.render(show_hint=not args.no_hints))
    if args.show_accepted:
        for f in accepted:
            print(f"[baselined] {f.render(show_hint=False)}")
    for m, line, p in problems:
        print(f"{m}:{line}: warning: {p}")
    for ent in stale:
        print(
            f"warning: stale baseline entry ({ent['code']} {ent['module']}::"
            f"{ent['symbol']}) matches nothing — remove it"
        )
    summary = (
        f"dabtlint: {len(new)} new finding{'s' if len(new) != 1 else ''}, "
        f"{len(accepted)} baselined, {len(suppressed)} suppressed"
    )
    if stale:
        summary += f", {len(stale)} stale baseline entr{'y' if len(stale) == 1 else 'ies'}"
    print(summary)
    return 1 if new else 0


if __name__ == "__main__":
    raise SystemExit(main())
