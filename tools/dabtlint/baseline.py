"""Baseline file: accepted pre-existing findings, each with a justification.

CI gates on *new* findings only: a finding whose ``(code, module, symbol,
detail)`` key appears in the baseline is reported as accepted, everything
else fails the run.  Two hard rules keep the baseline honest:

- every entry MUST carry a non-empty ``justification`` that does not start
  with "TODO" — ``--write-baseline`` emits TODO stubs precisely so an
  unjustified refresh cannot silently pass CI;
- stale entries (matching nothing on the current tree) are reported so the
  baseline shrinks as code improves (warn-only: a fix should not turn CI red).

The ``witness`` section is the runtime half of the same contract: lock
classes (named by their creation site, ``path::target``) under which the
lock-order witness accepts Future resolution.  See dabtlint/witness.py.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

from .findings import Finding


class BaselineError(ValueError):
    pass


@dataclasses.dataclass
class Baseline:
    path: str
    entries: List[dict]
    witness: Dict[str, str]  # lock creation-site name -> justification

    @classmethod
    def load(cls, path: str) -> "Baseline":
        if not os.path.exists(path):
            return cls(path, [], {})
        with open(path, "r", encoding="utf-8") as fh:
            try:
                data = json.load(fh)
            except json.JSONDecodeError as e:
                raise BaselineError(f"{path}: not valid JSON ({e})") from e
        entries = data.get("findings", [])
        witness = data.get("witness", {})
        for i, ent in enumerate(entries):
            missing = {"code", "module", "symbol", "detail"} - set(ent)
            if missing:
                raise BaselineError(
                    f"{path}: entry {i} is missing {sorted(missing)}"
                )
            just = (ent.get("justification") or "").strip()
            if not just or just.upper().startswith("TODO"):
                raise BaselineError(
                    f"{path}: entry {i} ({ent['code']} {ent['module']}::"
                    f"{ent['symbol']}) has no justification — every accepted "
                    "finding must say WHY it is safe"
                )
        for lock, just in witness.items():
            just = (just or "").strip()
            if not just or just.upper().startswith("TODO"):
                raise BaselineError(
                    f"{path}: witness entry {lock!r} has no justification"
                )
        return cls(path, entries, dict(witness))

    def _keys(self) -> Dict[Tuple[str, str, str, str], dict]:
        return {
            (e["code"], e["module"], e["symbol"], e["detail"]): e
            for e in self.entries
        }

    def split(
        self, findings: Sequence[Finding]
    ) -> Tuple[List[Finding], List[Finding], List[dict]]:
        """(new, accepted, stale_entries)."""
        keys = self._keys()
        matched = set()
        new: List[Finding] = []
        accepted: List[Finding] = []
        for f in findings:
            if f.key in keys:
                matched.add(f.key)
                accepted.append(f)
            else:
                new.append(f)
        stale = [e for k, e in keys.items() if k not in matched]
        return new, accepted, stale

    @staticmethod
    def write(
        path: str,
        findings: Sequence[Finding],
        *,
        keep: Optional["Baseline"] = None,
    ) -> int:
        """Write the current finding set as a baseline.  Entries already in
        ``keep`` that still match keep their justification; new entries get a
        TODO stub (the loader rejects stubs, forcing a human sentence)."""
        prior = keep._keys() if keep is not None else {}
        entries = []
        for f in sorted(set(x.key for x in findings)):
            code, module, symbol, detail = f
            old = prior.get(f)
            entries.append(
                {
                    "code": code,
                    "module": module,
                    "symbol": symbol,
                    "detail": detail,
                    "justification": (
                        old["justification"]
                        if old is not None
                        else "TODO: justify or fix"
                    ),
                }
            )
        data = {
            "findings": entries,
            "witness": dict(keep.witness) if keep is not None else {},
        }
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(data, fh, indent=2, sort_keys=False)
            fh.write("\n")
        return len(entries)
